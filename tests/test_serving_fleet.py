"""Serving fleet (docs/SERVING.md fleet section): scatter-gather
reads with row-scoped partial-failure containment, request batching
boundaries, the hot-response cache's freshness + forced-invalidation
rules, the IVF neighbors index, the /v1/status fleet view, and the
reshard-mid-serving no-stale-results regression."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.serving.ann import IVFIndex
from multiverso_tpu.serving.batch import (BatchedTableReader,
                                          HotRowCache,
                                          UpstreamReadError,
                                          request_meta)
from multiverso_tpu.serving.frontend import ServingFrontend
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.dashboard import samples


def _get(url, timeout=15):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _http_error(url, timeout=15):
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(url, timeout=timeout)
    err = exc.value
    body = json.loads(err.read())
    return err.code, dict(err.headers), body


# ---------------------------------------------------------------------------
# scatter-gather read path (tables/matrix_table.py read_rows_scatter)
# ---------------------------------------------------------------------------

class TestScatterRead:
    def test_values_versions_and_cache_flags(self):
        mv.init([])
        set_flag("max_get_staleness", 8)
        try:
            table = mv.create_matrix_table(64, 4)
            expected = np.arange(64 * 4, dtype=np.float32) \
                .reshape(64, 4)
            table.add_rows(np.arange(64, dtype=np.int32), expected)
            values, info = table.read_rows_scatter(
                np.asarray([3, 5, 3, 60], np.int32))
            assert (info["rows"] == [3, 5, 60]).all()
            np.testing.assert_allclose(values, expected[[3, 5, 60]])
            assert info["failed"].size == 0 and info["retryable"]
            assert (info["versions"] >= 0).all()
            assert not info["cached"].any()  # first read fetched
            values2, info2 = table.read_rows_scatter(
                np.asarray([3, 5, 60], np.int32))
            np.testing.assert_allclose(values2, expected[[3, 5, 60]])
            assert info2["cached"].all()
        finally:
            set_flag("max_get_staleness", 0)
            mv.shutdown()

    def test_cache_disabled_still_serves(self):
        mv.init([])  # default flags: no client cache
        try:
            table = mv.create_matrix_table(32, 4)
            expected = np.ones((32, 4), np.float32)
            table.add_rows(np.arange(32, dtype=np.int32), expected)
            values, info = table.read_rows_scatter(
                np.asarray([1, 2], np.int32))
            np.testing.assert_allclose(values, expected[[1, 2]])
            assert not info["cached"].any()
            assert info["failed"].size == 0
        finally:
            mv.shutdown()

    def test_concurrent_reads_stay_exact_under_a_trainer(self):
        """Any number of scatter reads may be in flight concurrently
        (no shared destination registers) while a trainer Adds; the
        per-row staleness invariant holds on every result."""
        mv.init([])
        set_flag("max_get_staleness", 8)
        try:
            table = mv.create_matrix_table(64, 4)
            expected = np.arange(64 * 4, dtype=np.float32) \
                .reshape(64, 4)
            table.add_rows(np.arange(64, dtype=np.int32), expected)
            stop = threading.Event()
            errors = []

            def trainer():
                while not stop.is_set():
                    table.add_rows(np.asarray([1], np.int32),
                                   np.ones((1, 4), np.float32))

            def reader(seed):
                rng = np.random.default_rng(seed)
                try:
                    for _ in range(100):
                        req = rng.integers(0, 64, 5).astype(np.int32)
                        values, info = table.read_rows_scatter(req)
                        assert info["failed"].size == 0
                        for p, row in enumerate(info["rows"]):
                            if row != 1:  # the trainer's moving row
                                np.testing.assert_allclose(
                                    values[p], expected[row])
                            version = int(info["versions"][p])
                            owner = int(info["owners"][p])
                            if version >= 0:
                                assert info["latest_by_sid"][owner] \
                                    - version <= 8
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            t = threading.Thread(target=trainer)
            readers = [threading.Thread(target=reader, args=(i,))
                       for i in range(4)]
            t.start()
            for r in readers:
                r.start()
            for r in readers:
                r.join()
            stop.set()
            t.join()
            assert not errors, errors
        finally:
            set_flag("max_get_staleness", 0)
            mv.shutdown()


# ---------------------------------------------------------------------------
# scatter-gather partial failure: dead/silent shard owner
# ---------------------------------------------------------------------------

def _drop_gets_toward(zoo, dead_rank, table_id):
    """Monkeypatch the rank's communicator to swallow Request_Get
    shards toward ``dead_rank`` for ``table_id`` — the observable
    shape of a dead/unreachable shard owner (with -rpc_timeout_s the
    sub-request fails typed-retryably instead of blocking). Returns
    an undo callable."""
    from multiverso_tpu.core.message import MsgType
    comm = zoo._actors["communicator"]
    original = comm.receive

    def dropping(msg):
        if (msg.type == MsgType.Request_Get and msg.dst == dead_rank
                and msg.table_id == table_id):
            return  # vanishes: the owner never sees it
        original(msg)

    comm.receive = dropping

    def undo():
        comm.receive = original

    return undo


class TestScatterPartialFailure:
    def test_dead_owner_fails_only_its_rows(self):
        """2-server cluster, one owner silenced: the silenced shard's
        rows fail retryably; every other row serves EXACTLY — never a
        wrong value — and a follow-up read after heal succeeds."""
        def body(rank):
            table = mv.create_matrix_table(24, 3)
            if table is None:
                mv.current_zoo().barrier()
                return None
            expected = np.arange(24 * 3, dtype=np.float32) \
                .reshape(24, 3)
            table.add_rows(np.arange(24, dtype=np.int32), expected)
            # sid0 owns rows 0-11 (this rank), sid1 owns 12-23
            # (rank 1). Silence rank 1.
            undo = _drop_gets_toward(mv.current_zoo(), 1,
                                     table.table_id)
            try:
                values, info = table.read_rows_scatter(
                    np.asarray([2, 5, 14, 20], np.int32))
            finally:
                undo()
            out = {
                "failed": sorted(int(r) for r in info["failed"]),
                "retryable": bool(info["retryable"]),
                "healthy_exact": bool(
                    np.allclose(values[0], expected[2])
                    and np.allclose(values[1], expected[5]))}
            # Heal: the same read now serves everything.
            values2, info2 = table.read_rows_scatter(
                np.asarray([2, 5, 14, 20], np.int32))
            out["healed"] = bool(
                info2["failed"].size == 0
                and np.allclose(values2, expected[[2, 5, 14, 20]]))
            mv.current_zoo().barrier()
            return out

        cluster = LocalCluster(2, argv=["-rpc_timeout_s=0.8"],
                               roles=["all", "server"])
        result = cluster.run(body)[0]
        assert result["failed"] == [14, 20]
        assert result["retryable"] is True
        assert result["healthy_exact"] is True
        assert result["healed"] is True

    def test_frontend_maps_partial_failure_to_503_on_affected_rows(
            self):
        """HTTP shape of the same failure: requests touching the dead
        owner's rows answer 503 + Retry-After naming failed_rows;
        requests on healthy shards answer 200 with exact values."""
        def body(rank):
            table = mv.create_matrix_table(24, 3)
            if table is None:
                mv.current_zoo().barrier()
                return None
            expected = np.arange(24 * 3, dtype=np.float32) \
                .reshape(24, 3)
            table.add_rows(np.arange(24, dtype=np.int32), expected)
            frontend = ServingFrontend(mv.current_zoo(), port=0,
                                       host="127.0.0.1")
            frontend.register_table("emb", table)
            base = f"http://127.0.0.1:{frontend.port}"
            out = {}
            undo = _drop_gets_toward(mv.current_zoo(), 1,
                                     table.table_id)
            try:
                status, _, doc = _get(base
                                      + "/v1/tables/emb/rows?ids=2,5")
                out["healthy_status"] = status
                out["healthy_exact"] = bool(np.allclose(
                    np.asarray(doc["rows"]), expected[[2, 5]]))
                code, headers, body_doc = _http_error(
                    base + "/v1/tables/emb/rows?ids=5,14")
                out["failed_status"] = code
                out["retry_after"] = headers.get("Retry-After")
                out["failed_rows"] = body_doc.get("failed_rows")
                out["retryable"] = body_doc.get("retryable")
            finally:
                undo()
                frontend.stop()
            mv.current_zoo().barrier()
            return out

        cluster = LocalCluster(2, argv=["-rpc_timeout_s=0.8"],
                               roles=["all", "server"])
        result = cluster.run(body)[0]
        assert result["healthy_status"] == 200
        assert result["healthy_exact"] is True
        assert result["failed_status"] == 503
        assert result["retry_after"] is not None
        assert result["failed_rows"] == [14]
        assert result["retryable"] is True


# ---------------------------------------------------------------------------
# request batching (serving/batch.py BatchedTableReader)
# ---------------------------------------------------------------------------

class _FakeScatterTable:
    """Duck-typed stand-in for MatrixWorker on the scatter contract:
    deterministic values, per-call recording, optional latency and
    scripted row failures."""

    def __init__(self, num_row=64, num_col=3, delay_s=0.0,
                 fail_rows=(), fatal_rows=()):
        self.num_row = num_row
        self.num_col = num_col
        self.delay_s = delay_s
        self.fail_rows = set(int(r) for r in fail_rows) \
            | set(int(r) for r in fatal_rows)
        self.fatal_rows = set(int(r) for r in fatal_rows)
        self.calls = []
        self.generation = 0
        self.latest = 5

    def value_of(self, row):
        return np.full(self.num_col, float(row), np.float32)

    def read_rows_scatter(self, row_ids):
        rows = np.unique(np.asarray(row_ids, np.int32))
        self.calls.append(rows)
        if self.delay_s:
            time.sleep(self.delay_s)
        values = np.stack([self.value_of(int(r)) for r in rows])
        failed = np.asarray(sorted(self.fail_rows
                                   & set(int(r) for r in rows)),
                            np.int32)
        fatal = np.asarray(sorted(self.fatal_rows
                                  & set(int(r) for r in rows)),
                           np.int32)
        return values, {
            "rows": rows,
            "versions": np.full(rows.size, self.latest, np.int64),
            "owners": np.zeros(rows.size, np.int64),
            "cached": np.zeros(rows.size, bool),
            "latest_by_sid": {0: self.latest},
            "failed": failed, "failed_fatal": fatal,
            "retryable": fatal.size == 0,
            "generation": self.generation}

    # HotRowCache probes
    def cache_generation(self):
        return self.generation

    def observed_versions(self):
        return {0: self.latest}


class TestBatching:
    def _reader(self, table, window_ms, max_rows=1024):
        return BatchedTableReader("t", table, lambda: 8,
                                  window_ms=window_ms,
                                  max_rows=max_rows)

    def test_lone_request_flushes_on_the_window_deadline(self):
        table = _FakeScatterTable()
        reader = self._reader(table, window_ms=40.0)
        try:
            t0 = time.perf_counter()
            values, meta, _ = reader.read(np.asarray([7, 3, 7]))
            elapsed = time.perf_counter() - t0
            # Never longer than the window plus scheduling slack —
            # the lone-request latency bound IS the window.
            assert elapsed < 1.0
            np.testing.assert_allclose(
                values, np.stack([table.value_of(7),
                                  table.value_of(3),
                                  table.value_of(7)]))
            assert meta["rows_requested"] == 2
            assert reader.batches == 1
        finally:
            reader.stop()

    def test_concurrent_requests_fold_into_one_scatter_call(self):
        table = _FakeScatterTable()
        reader = self._reader(table, window_ms=80.0)
        results, errors = {}, []

        def client(i):
            try:
                ids = np.asarray([i, i + 10])
                values, meta, _ = reader.read(ids)
                results[i] = values
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        try:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            for i in range(8):
                np.testing.assert_allclose(
                    results[i],
                    np.stack([table.value_of(i),
                              table.value_of(i + 10)]))
            # 8 concurrent requests inside one 80 ms window: folded
            # into far fewer merged reads (usually exactly 1; the
            # first may slip into its own batch under scheduling).
            assert reader.batches <= 2
            assert reader.requests == 8
            assert len(table.calls) == reader.batches
            assert samples("SERVING_BATCH_SIZE").count > 0
        finally:
            reader.stop()

    def test_size_cap_flushes_before_the_window(self):
        table = _FakeScatterTable()
        # A 10-SECOND window: only the size cap can flush this batch
        # quickly. 4 requests x 4 unique rows reach the 16-row cap.
        reader = self._reader(table, window_ms=10_000.0, max_rows=16)
        done = []

        def client(i):
            ids = np.arange(i * 4, i * 4 + 4)
            reader.read(ids)
            done.append(i)

        try:
            t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert time.perf_counter() - t0 < 5.0  # not the window
            assert len(done) == 4
        finally:
            reader.stop()

    def test_batch_error_isolation(self):
        """One request's failed rows fail THAT response; batch
        siblings (and non-failed rows generally) are unaffected."""
        table = _FakeScatterTable(fail_rows={5})
        reader = self._reader(table, window_ms=60.0)
        outcome = {}

        def good():
            values, meta, _ = reader.read(np.asarray([1, 2]))
            outcome["good"] = values

        def bad():
            try:
                reader.read(np.asarray([5, 6]))
                outcome["bad"] = "no error"
            except UpstreamReadError as exc:
                outcome["bad"] = (exc.rows, exc.retryable)

        try:
            threads = [threading.Thread(target=good),
                       threading.Thread(target=bad)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            np.testing.assert_allclose(
                outcome["good"], np.stack([table.value_of(1),
                                           table.value_of(2)]))
            assert outcome["bad"] == ([5], True)
        finally:
            reader.stop()

    def test_retryability_is_per_member_not_per_batch(self):
        """A fatal failure in one batch member must not demote a
        SIBLING member's transient (retryable) failure to a hard
        error — retryability follows each request's own rows."""
        table = _FakeScatterTable(fail_rows={5}, fatal_rows={20})
        reader = self._reader(table, window_ms=60.0)
        outcome = {}

        def transient():
            try:
                reader.read(np.asarray([5, 6]))
            except UpstreamReadError as exc:
                outcome["transient"] = (exc.rows, exc.retryable)

        def fatal():
            try:
                reader.read(np.asarray([20, 21]))
            except UpstreamReadError as exc:
                outcome["fatal"] = (exc.rows, exc.retryable)

        try:
            threads = [threading.Thread(target=transient),
                       threading.Thread(target=fatal)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert outcome["transient"] == ([5], True)
            assert outcome["fatal"] == ([20], False)
        finally:
            reader.stop()

    def test_window_zero_serves_inline(self):
        table = _FakeScatterTable()
        reader = self._reader(table, window_ms=0.0)
        values, meta, _ = reader.read(np.asarray([4]))
        np.testing.assert_allclose(values, [table.value_of(4)])
        assert reader._thread is None  # no batcher thread at all
        reader.stop()

    def test_request_meta_staleness_fields(self):
        info = {"versions": np.asarray([3, -1, 7], np.int64),
                "owners": np.asarray([0, 0, 1], np.int64),
                "cached": np.asarray([True, False, False]),
                "latest_by_sid": {0: 9, 1: 7}}
        meta = request_meta(info, np.arange(3), bound=8)
        assert meta["served_version"] == 3  # the -1 reads as latest
        assert meta["latest_version"] == 9
        assert meta["max_staleness"] == 6  # 9 - 3
        assert meta["cache_hit"] is False
        assert meta["rows_requested"] == 3
        assert meta["rows_cached"] == 1


# ---------------------------------------------------------------------------
# hot-response cache (serving/batch.py HotRowCache)
# ---------------------------------------------------------------------------

def _detail_for(table, rows):
    rows = np.asarray(rows, np.int32)
    return {"rows": rows,
            "values": np.stack([table.value_of(int(r))
                                for r in rows]),
            "versions": np.full(rows.size, table.latest, np.int64),
            "owners": np.zeros(rows.size, np.int64),
            "generation": table.generation}


class TestHotRowCache:
    def test_store_lookup_roundtrip_with_duplicates(self):
        table = _FakeScatterTable()
        cache = HotRowCache(table, lambda: 8, capacity=16)
        assert cache.lookup(np.asarray([3, 5])) is None  # cold
        cache.store(_detail_for(table, [3, 5]))
        served = cache.lookup(np.asarray([5, 3, 5]))
        assert served is not None
        rendered, meta = served
        np.testing.assert_allclose(
            np.asarray(rendered),
            np.stack([table.value_of(5), table.value_of(3),
                      table.value_of(5)]))
        assert meta["cache_hit"] is True
        assert meta["rows_requested"] == 2
        assert meta["max_staleness"] == 0
        # Partial coverage is a miss (all-or-nothing).
        assert cache.lookup(np.asarray([3, 9])) is None

    def test_staleness_bound_invalidates(self):
        table = _FakeScatterTable()
        cache = HotRowCache(table, lambda: 4, capacity=16)
        cache.store(_detail_for(table, [3]))
        assert cache.lookup(np.asarray([3])) is not None
        table.latest += 4  # aged exactly to the bound: still serves
        assert cache.lookup(np.asarray([3])) is not None
        table.latest += 1  # past it
        assert cache.lookup(np.asarray([3])) is None

    def test_generation_change_forces_invalidation(self):
        """A reshard/rejoin (generation bump) invalidates even though
        version arithmetic says fresh — the satellite-1 rule."""
        table = _FakeScatterTable()
        cache = HotRowCache(table, lambda: 8, capacity=16)
        cache.store(_detail_for(table, [3]))
        assert cache.lookup(np.asarray([3])) is not None
        table.generation += 1  # versions untouched
        assert cache.lookup(np.asarray([3])) is None

    def test_capacity_eviction(self):
        table = _FakeScatterTable()
        cache = HotRowCache(table, lambda: 8, capacity=4)
        cache.store(_detail_for(table, [0, 1, 2, 3, 4, 5]))
        assert cache.stats["rows"] == 4

    def test_lru_promotion_keeps_the_hot_head(self):
        """A row served from the cache (never re-stored) must not
        stay oldest in the eviction order: hits promote, so capacity
        overflow evicts the coldest row, not the hottest."""
        table = _FakeScatterTable()
        cache = HotRowCache(table, lambda: 8, capacity=3)
        cache.store(_detail_for(table, [0, 1, 2]))
        assert cache.lookup(np.asarray([0])) is not None  # promote 0
        cache.store(_detail_for(table, [3]))  # overflow: evict...
        assert cache.lookup(np.asarray([0])) is not None  # ...not 0
        assert cache.lookup(np.asarray([1])) is None  # the coldest


# ---------------------------------------------------------------------------
# the data-generation counter (tables/table_interface.py)
# ---------------------------------------------------------------------------

class TestDataGeneration:
    def test_regression_and_shard_move_both_bump(self):
        mv.init([])
        try:
            table = mv.create_matrix_table(16, 2)
            table.add_rows(np.asarray([0], np.int32),
                           np.ones((1, 2), np.float32))
            gen0 = table.cache_generation()
            table.note_version(0, 100)
            assert table.cache_generation() == gen0  # growth: no bump
            table.note_version(0, 50)  # REGRESSION: server rejoin
            assert table.cache_generation() == gen0 + 1
            table.note_shard_moved(0)  # reshard epoch change
            assert table.cache_generation() == gen0 + 2
        finally:
            mv.shutdown()


# ---------------------------------------------------------------------------
# IVF neighbors index (serving/ann.py)
# ---------------------------------------------------------------------------

def _clustered(n, dim, n_clusters, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_clusters, dim)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1)[:, None]
    assign = rng.integers(0, n_clusters, n)
    values = centers[assign] \
        + 0.05 * rng.standard_normal((n, dim)).astype(np.float32)
    return values.astype(np.float32)


def _brute_topk(values, norms, row, k):
    q = values[row]
    scores = (values @ q) / (norms * max(np.linalg.norm(q), 1e-12))
    scores[row] = -np.inf
    top = np.argpartition(-scores, k)[:k]
    return top[np.argsort(-scores[top])]


class TestIVFIndex:
    def test_full_probe_matches_brute_exactly(self):
        values = _clustered(512, 16, 8, seed=3)
        norms = np.maximum(np.linalg.norm(values, axis=1), 1e-12)
        index = IVFIndex(values, norms, nlist=8)
        for row in (0, 17, 400):
            ids, scores, scanned = index.search(
                values[row], 10, nprobe=8, exclude=row)
            assert scanned == 511  # every row except the query
            brute = _brute_topk(values.copy(), norms, row, 10)
            assert list(ids) == list(brute)

    def test_small_nprobe_high_recall_on_clustered_data(self):
        values = _clustered(2048, 16, 32, seed=4)
        norms = np.maximum(np.linalg.norm(values, axis=1), 1e-12)
        index = IVFIndex(values, norms, nlist=32)
        hits = total = 0
        for row in range(0, 200, 10):
            ids, _, scanned = index.search(values[row], 10, nprobe=4,
                                           exclude=row)
            assert scanned < 2048 / 2  # really pruned
            brute = set(int(i) for i in
                        _brute_topk(values.copy(), norms, row, 10))
            hits += len(brute & set(int(i) for i in ids))
            total += 10
        assert hits / total >= 0.95

    def test_nlist_larger_than_table_clamps(self):
        values = _clustered(10, 4, 2, seed=5)
        norms = np.maximum(np.linalg.norm(values, axis=1), 1e-12)
        index = IVFIndex(values, norms, nlist=64)
        assert index.nlist == 10
        ids, _, _ = index.search(values[0], 3, nprobe=10, exclude=0)
        assert len(ids) == 3 and 0 not in ids

    def test_nlist_clamps_to_the_kmeans_sample(self, monkeypatch):
        """On a table bigger than the k-means training sample, nlist
        must clamp to the SAMPLE (each centroid seeds on a distinct
        training row), not just the table size."""
        from multiverso_tpu.serving import ann as ann_mod
        monkeypatch.setattr(ann_mod, "_KMEANS_SAMPLE", 32)
        values = _clustered(100, 4, 4, seed=8)
        norms = np.maximum(np.linalg.norm(values, axis=1), 1e-12)
        index = IVFIndex(values, norms, nlist=64)  # 32 < 64 < 100
        assert index.nlist == 32
        ids, _, scanned = index.search(values[0], 5, nprobe=32,
                                       exclude=0)
        assert len(ids) == 5 and scanned == 99


# ---------------------------------------------------------------------------
# frontend integration: ANN endpoint, fleet status
# ---------------------------------------------------------------------------

@pytest.fixture
def fleet_env():
    mv.init([])
    set_flag("max_get_staleness", 8)
    set_flag("ann_nlist", 8)
    set_flag("serving_fleet_interval_s", 0.1)
    table = mv.create_matrix_table(256, 8)
    values = _clustered(256, 8, 8, seed=6)
    table.add_rows(np.arange(256, dtype=np.int32), values)
    frontend = ServingFrontend(mv.current_zoo(), port=0,
                               host="127.0.0.1")
    frontend.register_table("emb", table)
    yield frontend, table, f"http://127.0.0.1:{frontend.port}", values
    frontend.stop()
    set_flag("max_get_staleness", 0)
    set_flag("ann_nlist", 0)
    set_flag("serving_fleet_interval_s", 2.0)
    mv.shutdown()


class TestFrontendFleet:
    def test_ivf_endpoint_and_brute_escape_agree(self, fleet_env):
        frontend, table, base, values = fleet_env
        _, _, ivf = _get(base + "/v1/tables/emb/neighbors"
                              "?id=7&k=5&nprobe=8")
        assert ivf["index"]["kind"] == "ivf"
        assert ivf["index"]["nlist"] == 8
        _, _, brute = _get(base + "/v1/tables/emb/neighbors"
                                "?id=7&k=5&brute=1")
        assert brute["index"]["kind"] == "brute"
        # Full probe == exact: identical ranking.
        assert [n["id"] for n in ivf["neighbors"]] \
            == [n["id"] for n in brute["neighbors"]]
        assert samples("ANN_PROBE_MS").count > 0

    def test_status_carries_rank_and_fleet_aggregate(self, fleet_env):
        frontend, table, base, values = fleet_env
        deadline = time.monotonic() + 5.0
        fleet = None
        while time.monotonic() < deadline:
            _, _, status = _get(base + "/v1/status")
            fleet = status["fleet"]
            if fleet is not None:
                break
            time.sleep(0.05)
        assert status["rank"] == 0
        assert fleet is not None, "fleet view never arrived"
        assert fleet["aggregate"]["frontends"] == 1
        assert "0" in fleet["frontends"]
        assert fleet["aggregate"]["shed"] == 0

    def test_hot_cache_marks_response_and_skips_table(self, fleet_env):
        frontend, table, base, values = fleet_env
        url = base + "/v1/tables/emb/rows?ids=11,13"
        _, _, first = _get(url)
        assert first["response_cache"] == "miss"
        _, headers, second = _get(url)
        assert second["response_cache"] == "hit"
        assert second["cache_hit"] is True
        assert headers["X-MV-Cache"] == "hit"
        np.testing.assert_allclose(np.asarray(second["rows"]),
                                   np.asarray(first["rows"]))
        entry = frontend._entry("emb")
        assert entry.hot.stats["hits"] >= 1


# ---------------------------------------------------------------------------
# the satellite-1 regression: reshard mid-serving must not serve
# stale neighbors or stale hot-cache rows
# ---------------------------------------------------------------------------

class TestReshardMidServing:
    def test_no_stale_results_after_reshard(self):
        def body(rank):
            table = mv.create_matrix_table(24, 4)
            if table is None:
                mv.current_zoo().barrier()
                return None
            # Rows 20/21 are the probes: pre-reshard row 20 is
            # parallel to the query row 0, row 21 orthogonal.
            base = np.zeros((24, 4), np.float32)
            base[:, 2] = 1.0
            base[0] = [1, 0, 0, 0]
            base[20] = [0.9, 0.1, 0, 0]
            base[21] = [0, 0, 0, 1]
            table.add_rows(np.arange(24, dtype=np.int32), base)
            frontend = ServingFrontend(mv.current_zoo(), port=0,
                                       host="127.0.0.1")
            frontend.register_table("emb", table)
            api = f"http://127.0.0.1:{frontend.port}/v1/tables/emb"
            out = {}
            try:
                _, _, pre = _get(api + "/neighbors?id=0&k=1")
                out["pre_top"] = pre["neighbors"][0]["id"]
                _, _, row_pre = _get(api + "/rows?ids=20")
                _, _, row_pre2 = _get(api + "/rows?ids=20")
                out["hot_warm"] = row_pre2["response_cache"]
                # Grow the fleet: rows 16-23 (incl. both probes) move
                # to the standby server 2, whose shard version counter
                # starts BELOW the index/cache anchors — version
                # staleness alone would claim everything fresh.
                mv.reshard_table(table, [0, 1, 2], wait_s=60.0)
                # Flip the probes: row 20 -> orthogonal, row 21 ->
                # parallel. Few adds, far inside the staleness bound.
                table.add_rows(
                    np.asarray([20, 21], np.int32),
                    np.asarray([[-0.9, -0.1, 0, 1],
                                [1, 0, 0, -1]], np.float32))
                _, _, post = _get(api + "/neighbors?id=0&k=1")
                out["post_top"] = post["neighbors"][0]["id"]
                out["post_refreshed"] = post["index_refreshed"]
                _, _, row_post = _get(api + "/rows?ids=20")
                out["row_current"] = bool(np.allclose(
                    np.asarray(row_post["rows"][0]),
                    [0.0, 0.0, 0.0, 1.0], atol=1e-5))
                out["row_stale_copy"] = row_post["rows"][0] \
                    == row_pre["rows"][0]
            finally:
                frontend.stop()
            mv.current_zoo().barrier()
            return out

        cluster = LocalCluster(3, argv=["-shard_initial_servers=2",
                                        "-max_get_staleness=8"],
                               roles=["all", "server", "server"])
        cluster.timeout = 240.0
        try:
            result = cluster.run(body)[0]
        finally:
            set_flag("max_get_staleness", 0)
            set_flag("shard_initial_servers", 0)
        assert result["pre_top"] == 20
        assert result["hot_warm"] == "hit"  # the cache WAS live
        assert result["post_top"] == 21, \
            "stale neighbors index served after reshard"
        assert result["post_refreshed"] is True
        assert result["row_current"] is True, \
            "stale hot-cache row served after reshard"
        assert result["row_stale_copy"] is False
