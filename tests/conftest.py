"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host platform with 8 virtual devices (the same trick the
driver's dryrun uses). Must run before the first jax import.
"""

import os

# Force, don't setdefault: the TPU environment pre-sets JAX_PLATFORMS to the
# hardware platform and its sitecustomize imports jax at interpreter start,
# so the env var alone is ignored — jax.config.update is the reliable path.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy chaos/bench tests, excluded from the tier-1 run "
        "(-m 'not slow')")


@pytest.fixture(autouse=True)
def _reset_flags():
    from multiverso_tpu.util import configure
    yield
    configure.reset_flags()
