"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host platform with 8 virtual devices (the same trick the
driver's dryrun uses). Must run before the first jax import.
"""

import os

# Force, don't setdefault: the TPU environment pre-sets JAX_PLATFORMS to the
# hardware platform and its sitecustomize imports jax at interpreter start,
# so the env var alone is ignored — jax.config.update is the reliable path.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy chaos/bench tests, excluded from the tier-1 run "
        "(-m 'not slow')")


@pytest.fixture(autouse=True)
def _reset_flags():
    from multiverso_tpu.util import configure
    yield
    configure.reset_flags()


@pytest.fixture(autouse=True)
def _transport_leak_guard():
    """Teardown leak guard (docs/THREADS.md): every test must return
    role-thread count to its baseline — a finalized transport leaves
    no loop, writer, or dispatch thread behind — and tests that built
    a transport must also return the process fd count to baseline
    (sockets, selector epoll fds, wake pipes, shm doorbell FIFOs)."""
    import gc
    import time

    from multiverso_tpu.runtime import thread_roles
    from multiverso_tpu.runtime.tcp import TcpNet

    def fd_count():
        try:
            return len(os.listdir("/proc/self/fd"))
        except OSError:  # pragma: no cover - no procfs
            return -1

    threads_before = sum(thread_roles.roles_alive().values())
    nets_before = TcpNet.instances_created
    fds_before = fd_count()
    yield
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if sum(thread_roles.roles_alive().values()) <= threads_before:
            break
        time.sleep(0.05)
    alive = thread_roles.roles_alive()
    assert sum(alive.values()) <= threads_before, (
        f"role threads leaked past teardown: {alive} "
        f"(baseline {threads_before})")
    if TcpNet.instances_created != nets_before and fds_before >= 0:
        # Scoped to transport-building tests: unrelated tests may
        # fault in lazy runtime fds (jax, imports) that are not leaks.
        gc.collect()  # drop lingering frame leases / socket wrappers
        fds_after = fd_count()
        assert fds_after <= fds_before + 8, (
            f"fd count grew {fds_before} -> {fds_after} across a "
            f"transport-building test (leaked sockets/pipes?)")
