"""Live elastic resharding tests (ISSUE 12, docs/SHARDING.md).

Four layers:

* unit tests for the shard-map algebra (epoch-0 equivalence to the
  frozen layout, move/coalesce, diff, planning), the migration state
  machines (dirty re-streaming, seq-gap detection, duplicate-chunk
  drops), the chaos harness's frame filter, and the auto-reshard skew
  planner;
* mid-stream equivalence: the 1-server element-wise equality checks of
  ``tests/test_sharding.py`` re-run ACROSS a live shard-map change —
  grow onto a standby server and drain it back, for matrix and KV
  tables with array/sparse siblings riding in the same cluster;
* a property test: no (Get, Add) interleaving across the handoff
  window observes a version regression without a generation change;
* the chaos matrix (``-m slow``, subprocess TCP clusters): SIGKILL the
  migration destination and the migration source mid-handoff, and
  partition the controller's shard control plane mid-move — every
  case ends in a consistent epoch (committed or rolled back) with
  element-wise table equality against the unperturbed expectation.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.message import Message, MsgType
from multiverso_tpu.runtime import replica as rm
from multiverso_tpu.runtime import shard_map as sm
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.tables import row_offsets
from multiverso_tpu.util import chaos
from multiverso_tpu.util.configure import set_flag

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def env():
    mv.init([])
    yield
    mv.shutdown()


# ---------------------------------------------------------------------------
# unit: shard-map algebra
# ---------------------------------------------------------------------------

class TestShardMap:
    @pytest.mark.parametrize("rows,servers", [(16, 2), (17, 3), (3, 4)])
    def test_initial_reproduces_frozen_layout(self, rows, servers):
        smap = sm.ShardMap.initial(rows, servers)
        offsets = row_offsets(rows, servers)
        assert smap.bounds.tolist() == offsets
        # The frozen division rule and the map agree on every row.
        keys = np.arange(rows, dtype=np.int64)
        length = max(rows // (len(offsets) - 1), 1)
        frozen = np.minimum(keys // length, len(offsets) - 2)
        np.testing.assert_array_equal(smap.owner_of(keys), frozen)

    def test_initial_active_subset(self):
        smap = sm.ShardMap.initial(16, 4, active=2)
        assert smap.bounds.tolist() == [0, 8, 16]
        assert smap.owner_sids() == [0, 1]

    def test_move_coalesces_and_bumps_epoch(self):
        smap = sm.ShardMap.initial(16, 2)  # [0,8)->0, [8,16)->1
        moved = smap.move(8, 12, 0)
        assert moved.epoch == 1
        # [8,12) joined server 0's adjacent range: coalesced.
        assert moved.bounds.tolist() == [0, 12, 16]
        assert moved.owners.tolist() == [0, 1]

    def test_diff_moved_merges_runs(self):
        a = sm.ShardMap.initial(12, 2)
        b = a.move(2, 6, 1)
        assert a.diff_moved(b) == [(2, 6, 0, 1)]
        assert b.diff_moved(b) == []

    def test_plan_moves_grow_shrink_roundtrip(self):
        smap = sm.ShardMap.initial(16, 3, active=2)
        grow = sm.plan_moves(smap, [0, 1, 2])
        assert grow  # something must move onto the standby
        for lo, hi, src, dst in grow:
            smap = smap.move(lo, hi, dst)
        assert smap.bounds.tolist() == row_offsets(16, 3)
        shrink = sm.plan_moves(smap, [0, 1])
        for lo, hi, src, dst in shrink:
            smap = smap.move(lo, hi, dst)
        assert smap.bounds.tolist() == row_offsets(16, 2)
        assert smap.owner_sids() == [0, 1]
        assert sm.plan_moves(smap, [0, 1]) == []  # already there

    def test_pack_unpack_roundtrip(self):
        smap = sm.ShardMap.initial(16, 2).move(3, 7, 1)
        blobs = smap.pack(table_id=4, alive_sids=[0, 1])
        table_id, got, alive = sm.ShardMap.unpack(blobs)
        assert table_id == 4 and got.epoch == smap.epoch
        np.testing.assert_array_equal(got.bounds, smap.bounds)
        np.testing.assert_array_equal(got.owners, smap.owners)
        assert alive.tolist() == [0, 1]


class TestMigrationState:
    def _mig(self, lo=0, hi=10, chunk=4):
        set_flag("reshard_chunk_rows", chunk)
        return sm.MigrationOut(0, lo, hi, src_sid=0, dst_sid=1,
                               dst_rank=1, epoch=1)

    def test_chunks_then_final_drains_dirty(self):
        mig = self._mig()
        seq0, rows0, fin0 = mig.next_chunk()
        assert (seq0, fin0) == (0, False) and rows0.tolist() == [0, 1, 2, 3]
        # An Add touching an already-sent row re-streams it; unsent
        # rows do not (their chunk will carry the new value anyway).
        mig.note_add(np.asarray([1, 9], dtype=np.int64))
        assert mig.dirty == {1}
        seq1, rows1, fin1 = mig.next_chunk()
        seq2, rows2, fin2 = mig.next_chunk()
        assert not fin1 and not fin2
        seqf, rowsf, finf = mig.next_chunk()
        assert finf and seqf == 3 and rowsf.tolist() == [1]
        assert mig.next_chunk() is None
        # Retransmission regathers any chunk, including the final.
        assert mig.rows_of_seq(1).tolist() == [4, 5, 6, 7]
        assert mig.rows_of_seq(3).tolist() == [1]

    def test_no_dirty_tracking_after_handoff(self):
        mig = self._mig(chunk=100)
        mig.next_chunk()  # the whole range
        mig.next_chunk()  # final
        mig.note_add(np.asarray([2], dtype=np.int64))
        assert mig.dirty == set()

    def test_in_gap_detection_and_duplicate_drop(self):
        mig = sm.MigrationIn(epoch=1, src_sid=0, src_rank=0, lo=0, hi=10)
        assert mig.note_applied(0)
        assert not mig.note_applied(0)  # duplicate/retransmit raced
        mig.n_chunks = 2  # final seq
        assert mig.note_applied(2)
        assert not mig.check_complete()
        assert mig.missing_seqs() == [1]
        assert mig.note_applied(1)
        assert mig.check_complete()


class TestChaosFilter:
    def _arm(self, spec):
        set_flag("chaos_frames", spec)
        # force the module to re-read the flag
        chaos._frames_spec = None

    def teardown_method(self):
        set_flag("chaos_frames", "")
        chaos._frames_spec = None

    def _msg(self, t=MsgType.Request_ShardData, dst=1):
        return Message(src=0, dst=dst, msg_type=t)

    def test_off_is_none(self):
        self._arm("")
        assert chaos.filter_frames(self._msg()) is None

    def test_drop_is_deterministic_and_scoped(self):
        self._arm("drop=1.0,classes=shard,seed=3")
        assert chaos.filter_frames(self._msg()) == []
        # Data-plane frames are out of scope for classes=shard.
        assert chaos.filter_frames(
            self._msg(MsgType.Request_Get)) is None

    def test_dst_scope(self):
        self._arm("drop=1.0,classes=all,dst=2")
        assert chaos.filter_frames(self._msg(dst=1)) is None
        assert chaos.filter_frames(self._msg(dst=2)) == []

    def test_reorder_holds_then_swaps(self):
        self._arm("reorder=1.0,classes=shard,seed=1")
        a, b = self._msg(), self._msg()
        assert chaos.filter_frames(a) == []      # held
        out = chaos.filter_frames(b)
        assert out == [b, a]                     # newer jumps the queue

    def test_window_closes(self):
        self._arm("drop=1.0,classes=shard,for_s=0.05")
        assert chaos.filter_frames(self._msg()) == []
        time.sleep(0.1)
        assert chaos.filter_frames(self._msg()) is None

    def test_kill_point_countdown_is_safe_below_target(self):
        set_flag("chaos_kill_on", "some_point:99")
        try:
            chaos.kill_point("other_point")  # no match: no-op
            chaos.kill_point("some_point")   # hit 1 of 99: survives
        finally:
            set_flag("chaos_kill_on", "")


class TestAutoReshardPlanner:
    class _FakeZoo:
        num_servers = 3
        net_size = 1
        rank = 0
        _actors: dict = {}

        def __init__(self):
            self.sent = []

        def server_rank(self, sid):
            return int(sid)

        def rank_to_server_id(self, rank):
            return int(rank)

        def send_to(self, name, msg):
            self.sent.append(msg)

    def test_skew_triggers_a_split_toward_the_coldest(self):
        set_flag("reshard_auto", True)
        set_flag("reshard_skew", 2.0)
        try:
            zoo = self._FakeZoo()
            mgr = sm.ReshardManager(zoo)
            hot_rows = np.asarray([1, 2], dtype=np.int32)
            counts = np.asarray([500, 400], dtype=np.int32)
            mgr.note_report(0, 0, hot_rows, counts, num_items=30)
            mgr.note_report(0, 1, np.asarray([12], np.int32),
                            np.asarray([3], np.int32), num_items=30)
            mgr.note_report(0, 2, np.asarray([22], np.int32),
                            np.asarray([2], np.int32), num_items=30)
            # Server 0 carries ~99% of the load: a move must be in
            # flight, sourced at 0, keeping the hottest row (1) at 0.
            assert mgr._pending is not None
            assert mgr._pending.src_sid == 0
            assert mgr._pending.dst_sid in (1, 2)
            assert not (mgr._pending.lo <= 1 < mgr._pending.hi)
            # The Begin actually left toward the source rank.
            assert any(m.type_int == int(MsgType.Request_ShardBegin)
                       for m in zoo.sent)
        finally:
            set_flag("reshard_auto", False)

    def test_balanced_load_plans_nothing(self):
        set_flag("reshard_auto", True)
        try:
            zoo = self._FakeZoo()
            mgr = sm.ReshardManager(zoo)
            for sid in range(3):
                mgr.note_report(0, sid, np.asarray([sid], np.int32),
                                np.asarray([100], np.int32),
                                num_items=30)
            assert mgr._pending is None and not mgr._queue
        finally:
            set_flag("reshard_auto", False)


class TestReplicaReconcile:
    def test_reconcile_revives_and_marks(self):
        # Satellite: dead-server marks are re-validated against the
        # controller's authoritative node table on every map broadcast
        # — a rejoined server resumes serving replicas WITHOUT waiting
        # for organic traffic.
        r = rm.ReplicaRouter(3, salt=0)
        r.apply(1, np.asarray([1, 2], np.int32))
        r.mark_dead(2)
        assert 2 in r._dead
        r.reconcile([0, 1, 2])
        assert r._dead == set()
        r.reconcile([0])  # controller says 1 and 2 are dead
        assert r._dead == {1, 2}

    def test_deactivated_router_ignores_later_maps(self):
        r = rm.ReplicaRouter(2)
        r.apply(1, np.asarray([3], np.int32))
        r.deactivate()
        assert not r.active
        assert not r.apply(2, np.asarray([4], np.int32))
        assert not r.active


class TestBeginRefusal:
    def test_sparse_and_stateful_refuse(self, env):
        sparse = mv.create_matrix_table(8, 2, is_sparse=True)
        momentum = mv.create_matrix_table(8, 2, updater_type="momentum")
        assert sparse.reshard_space() == 0  # worker-side guard
        zoo = mv.current_zoo()
        with pytest.raises(ValueError):
            zoo.reshard_table(sparse, [0])
        srv = zoo._actors["server"]
        desc = np.asarray([0, 4, 0, 1, 1, 1, 8], dtype=np.int64)
        assert not srv._store[sparse.table_id].shard_begin_out(desc)
        assert not srv._store[momentum.table_id].shard_begin_out(desc)
        arr = mv.create_array_table(16)
        with pytest.raises(ValueError):
            zoo.reshard_table(arr, [0])


class TestSnapshotElasticMeta:
    def test_matrix_meta_roundtrip(self, env):
        import io
        table = mv.create_matrix_table(8, 2)
        srv = mv.current_zoo()._actors["server"]._store[table.table_id]
        srv._overlay = {9: np.asarray([1.0, 2.0], np.float32)}
        srv._fwd = [(4, 6, 1, 1)]
        srv._smap = sm.ShardMap.initial(8, 1).move(4, 6, 1)
        meta = srv.snapshot_meta()
        assert meta["elastic"] == 1 and meta["shard_epoch"] == 1
        state = srv.snapshot_state()
        buf = io.BytesIO()
        srv.write_snapshot(state, buf)
        srv._overlay, srv._fwd = {}, []
        srv.load_with_meta(io.BytesIO(buf.getvalue()), meta)
        assert 9 in srv._overlay
        np.testing.assert_allclose(srv._overlay[9], [1.0, 2.0])
        assert srv._fwd == [(4, 6, 1, -1)]  # rank re-resolved (1 shard)


# ---------------------------------------------------------------------------
# mid-stream equivalence: 1-vs-N across a live shard-map change
# ---------------------------------------------------------------------------

def _elastic_workload(reshard: bool):
    """Matrix + KV + array + sparse in ONE cluster; the matrix and KV
    tables reshard mid-stream when asked (grow onto a standby, then
    drain back) while the array/sparse siblings keep trading — their
    results must be untouched by their neighbors' migrations."""
    def body(rank):
        rng = np.random.default_rng(21)
        matrix = mv.create_matrix_table(17, 3)
        kv = mv.create_kv_table()
        arr = mv.create_array_table(13)
        sparse = mv.create_matrix_table(10, 2, is_sparse=True)
        if matrix is None:
            mv.current_zoo().barrier()
            return None
        outs = []
        kv_keys = np.array([0, 1, 7, 100, 101, 10**6], np.int64)
        for step in range(6):
            ids = np.unique(rng.integers(0, 17, 10).astype(np.int32))
            matrix.add_rows(ids, rng.standard_normal(
                (ids.size, 3)).astype(np.float32))
            kv.add(kv_keys, rng.standard_normal(
                kv_keys.size).astype(np.float32))
            arr.add(rng.standard_normal(13).astype(np.float32))
            sids = np.unique(rng.integers(0, 10, 4).astype(np.int32))
            sparse.add_rows(sids, rng.standard_normal(
                (sids.size, 2)).astype(np.float32))
            if reshard and step == 2:
                mv.reshard_table(matrix, [0, 1, 2], wait_s=60.0)
                mv.reshard_table(kv, [0, 1, 2], wait_s=60.0)
            if reshard and step == 4:
                mv.reshard_table(matrix, [0, 1], wait_s=60.0)
            outs.append(matrix.get_rows(
                np.arange(17, dtype=np.int32)).copy())
            outs.append(matrix.get().copy())
            outs.append(np.asarray(
                [kv.get(kv_keys)[int(k)] for k in kv_keys]))
            outs.append(arr.get().copy())
            outs.append(sparse.get().copy())
        mv.current_zoo().barrier()
        return outs

    return body


class TestMidStreamEquivalence:
    def test_all_table_types_across_a_live_reshard(self):
        baseline = LocalCluster(1).run(_elastic_workload(False))[0]
        cluster = LocalCluster(3, argv=["-shard_initial_servers=2"],
                               roles=["all", "server", "server"])
        cluster.timeout = 240.0
        live = cluster.run(_elastic_workload(True))[0]
        assert len(baseline) == len(live)
        for i, (a, b) in enumerate(zip(baseline, live)):
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-6,
                                       err_msg=f"output {i}")

    def test_unsupported_table_nacks_and_rolls_back(self):
        # A reshard request aimed at an ARRAY table's id (crafted at
        # the controller: array tables never send one themselves) must
        # be refused by the server and rolled back without touching
        # anything — the rollback path proven without any process
        # death.
        def body(rank):
            from multiverso_tpu.core.blob import Blob
            from multiverso_tpu.runtime import actor as actors
            arr = mv.create_array_table(12)
            zoo = mv.current_zoo()
            if rank != 0:
                zoo.barrier()
                zoo.barrier()
                return None
            arr.add(np.ones(12, np.float32))
            zoo.barrier()
            msg = Message(src=zoo.rank, dst=0,
                          msg_type=MsgType.Control_Shard_Request,
                          table_id=arr.table_id)
            msg.push(Blob(np.asarray([12, 0, 0], dtype=np.int64)))
            zoo.send_to(actors.COMMUNICATOR, msg)
            deadline = time.monotonic() + 20
            ctrl = zoo._actors.get(actors.CONTROLLER)
            while time.monotonic() < deadline:
                if ctrl is not None and ctrl.reshards._pending is None \
                        and not ctrl.reshards._queue \
                        and ctrl.reshards.maps:
                    break
                time.sleep(0.05)
            # The map never advanced and the table still serves.
            assert ctrl.reshards.maps[arr.table_id].epoch == 0
            got = arr.get()
            mv.current_zoo().barrier()
            return got

        res = LocalCluster(2).run(body)
        np.testing.assert_allclose(res[0], np.full(12, 1.0, np.float32))


# ---------------------------------------------------------------------------
# property: version regressions only with a generation change
# ---------------------------------------------------------------------------

class TestHandoffVersionProperty:
    def test_no_regression_without_generation_change(self):
        """Across random (Get, Add) interleavings spanning two live
        migrations, every version stamp a worker observes per shard is
        monotone — the ONLY sanctioned discontinuity is the shard-map
        generation-change invalidation (note_shard_moved), and
        forwarded replies/acks are constructed so the tracker never
        sees a regression at all."""
        def body(rank):
            table = mv.create_matrix_table(16, 2)
            if table is None:
                mv.current_zoo().barrier()
                return None
            regressions = []
            gen_changes = []
            tracker = table._version_tracker
            orig_note = table.note_version

            def spy_note(sid, version):
                if tracker.regressed(sid, version):
                    regressions.append((sid, version,
                                        tracker.latest(sid)))
                orig_note(sid, version)

            orig_moved = table.note_shard_moved

            def spy_moved(old_sid):
                gen_changes.append(old_sid)
                orig_moved(old_sid)

            table.note_version = spy_note
            table.note_shard_moved = spy_moved
            rng = np.random.default_rng(9)
            did = [False, False]
            for step in range(120):
                ids = np.unique(rng.integers(0, 16, 6).astype(np.int32))
                if rng.random() < 0.5:
                    table.add_rows(ids, np.ones((ids.size, 2),
                                                np.float32))
                else:
                    table.get_rows(ids)
                if step == 40 and not did[0]:
                    did[0] = True
                    mv.reshard_table(table, [0, 1, 2], wait_s=60.0)
                if step == 80 and not did[1]:
                    did[1] = True
                    mv.reshard_table(table, [0, 2], wait_s=60.0)
            mv.current_zoo().barrier()
            return regressions, gen_changes

        cluster = LocalCluster(3, argv=["-shard_initial_servers=2"],
                               roles=["all", "server", "server"])
        cluster.timeout = 240.0
        regressions, gen_changes = cluster.run(body)[0]
        assert gen_changes, "the reshards never adopted a map"
        assert not regressions, \
            f"version regression without a generation change: " \
            f"{regressions}"


# ---------------------------------------------------------------------------
# chaos: controller partition mid-handoff (in-process; kills are slow)
# ---------------------------------------------------------------------------

class TestControllerPartition:
    def test_commit_survives_a_dropped_control_plane(self):
        """Partition the controller's shard control plane mid-handoff:
        every shard-class frame toward rank 0 drops for a window that
        opens at the destination's first Control_Shard_Done. The
        dual-read window carries traffic meanwhile (zero failed
        requests), the destination re-announces on traffic, and the
        commit lands once the partition heals — the migration
        COMPLETES rather than rolling back."""
        def body(rank):
            from multiverso_tpu.util.dashboard import Dashboard
            table = mv.create_matrix_table(16, 2)
            if table is None:
                mv.current_zoo().barrier()
                return None
            shadow = np.zeros((16, 2), np.float32)
            rng = np.random.default_rng(3)
            for _ in range(3):
                ids = np.unique(rng.integers(0, 16, 8).astype(np.int32))
                d = rng.standard_normal((ids.size, 2)).astype(np.float32)
                table.add_rows(ids, d)
                shadow[ids] += d
            failed = 0
            # Fire the reshard WITHOUT waiting, then keep reading
            # through the partitioned window.
            mv.current_zoo().reshard_table(table, [0, 1, 2], wait_s=0)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    got = table.get_rows(np.arange(16, dtype=np.int32))
                    if not np.allclose(got, shadow):
                        failed += 1
                except Exception:  # noqa: BLE001
                    failed += 1
                if table.shard_owner_sids() == [0, 1, 2]:
                    break
                time.sleep(0.02)
            dropped = Dashboard.get(chaos.CHAOS_DROPPED).count
            mv.current_zoo().barrier()
            return (failed, table.shard_owner_sids(),
                    table.shard_epoch(), dropped)

        cluster = LocalCluster(
            3,
            argv=["-shard_initial_servers=2",
                  "-chaos_frames=drop=1.0,classes=shard,dst=0,for_s=3,"
                  "seed=5"],
            roles=["all", "server", "server"])
        cluster.timeout = 240.0
        failed, owners, epoch, dropped = cluster.run(body)[0]
        set_flag("chaos_frames", "")
        chaos._frames_spec = None
        assert failed == 0, f"{failed} wrong/failed reads mid-partition"
        assert owners == [0, 1, 2], "commit never landed"
        assert epoch >= 1
        assert dropped > 0, "the partition never actually dropped"


# ---------------------------------------------------------------------------
# chaos matrix: kill the migration endpoints (subprocess TCP; slow)
# ---------------------------------------------------------------------------

_PRELUDE = """
import os, sys, time
import faulthandler
faulthandler.dump_traceback_later(500, exit=True)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import multiverso_tpu as mv
"""


def _spawn(body, log_path, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=REPO)
    env.update(extra_env or {})
    out = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _PRELUDE.format(repo=REPO) + body],
        env=env, stdout=out, stderr=subprocess.STDOUT, text=True)
    out.close()
    proc.log_path = log_path
    return proc


def _wait_logged(proc, timeout):
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    with open(proc.log_path) as f:
        return f.read()


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


#: Shared cluster script: rank 0 = worker + controller + server 0,
#: rank 1 = server 1, rank 2 = standby server 2 (the destination of a
#: grow). The worker seeds deterministic values, triggers the grow,
#: and reads the whole table with retries until the outcome settles.
#: Chaos processes coordinate through a DONE file, not barriers or
#: timers: a 3-way barrier can never complete with a SIGKILLed rank in
#: the set (the rejoin-grace failure would then tear down healthy
#: servers mid-test), and fixed timers drift under this one-core box's
#: 60-90s cluster/jit startup. The worker writes the file when its
#: verdict is printed; servers poll it and exit hard (a kill-matrix
#: harness has nothing graceful left to drain).
_CHAOS_COMMON = """
from multiverso_tpu.runtime.net import PeerLostError
rank = int(os.environ["MV_RANK"])
done_file = {done!r}
roles = {{0: "default", 1: "server", 2: "server"}}
flags = ["-machine_file={mf}", "-rank=" + str(rank),
         "-ps_role=" + roles[rank],
         "-shard_initial_servers=2",
         "-reshard_chunk_rows=4",
         "-heartbeat_interval_s=0.5", "-heartbeat_timeout_s=3",
         "-rejoin_grace_s=300",
         "-rpc_retry_max=60", "-rpc_backoff_ms=50",
         "-connect_timeout_s=5"] + {extra_flags!r}
mv.init(flags)
table = mv.create_matrix_table(16, 2)
"""

_CHAOS_WORKER_TAIL = """
expect = np.arange(32, dtype=np.float32).reshape(16, 2)
table.add(expect.copy())
got = table.get_rows(np.arange(16, dtype=np.int32))
assert np.array_equal(got, expect)
time.sleep({presleep})
mv.current_zoo().reshard_table(table, {target}, wait_s=0)
t0 = time.monotonic()
failed = 0
reads = 0
while time.monotonic() - t0 < {window}:
    try:
        got = table.get_rows(np.arange(16, dtype=np.int32))
        reads += 1
        if not np.array_equal(got, expect):
            failed += 1
            print("WRONG_VALUE", flush=True)
    except PeerLostError:
        time.sleep(0.2)  # retryable: the dead rank is restarting
    time.sleep(0.05)
final = table.get_rows(np.arange(16, dtype=np.int32))
print("READS", reads, "FAILED", failed, flush=True)
print("OWNERS", table.shard_owner_sids(), flush=True)
print("FINAL_EQUAL", bool(np.array_equal(final, expect)), flush=True)
print("WORKER_DONE", flush=True)
open(done_file, "w").write("done")
os._exit(0)
"""

_CHAOS_SERVER_TAIL = """
deadline = time.monotonic() + 400
while time.monotonic() < deadline and not os.path.exists(done_file):
    time.sleep(0.3)
print("SERVER_DONE", flush=True)
os._exit(0)
"""


def _chaos_cluster(tmp_path, per_rank_flags, window=25,
                   target=(0, 1, 2), presleep=0.0):
    target = list(target)
    ports = [_free_port() for _ in range(3)]
    mf = tmp_path / "machines"
    mf.write_text("".join(f"127.0.0.1:{p}\n" for p in ports))
    done = str(tmp_path / "worker.done")
    procs = []
    for r in range(3):
        body = _CHAOS_COMMON.format(mf=str(mf), done=done,
                                    extra_flags=per_rank_flags.get(r, []))
        body += _CHAOS_WORKER_TAIL.format(
            window=window, target=target, presleep=presleep) \
            if r == 0 else _CHAOS_SERVER_TAIL
        procs.append(_spawn(body, str(tmp_path / f"rank{r}.log"),
                            extra_env={"MV_RANK": str(r)}))
    return procs


@pytest.mark.slow
class TestChaosKillMatrix:
    def test_kill_migration_destination_rolls_back(self, tmp_path):
        """SIGKILL the DESTINATION the moment it applies the final
        chunk (pre-commit): the controller declares it dead, aborts
        the move at the source (which resumes ownership from its
        handoff copy), and the map stays at the pre-move epoch — with
        ZERO wrong-value reads throughout (the dest was a standby, so
        every row keeps serving)."""
        procs = _chaos_cluster(
            tmp_path,
            {2: ["-chaos_kill_on=shard_dest_final"]})
        out0 = _wait_logged(procs[0], 420)
        procs[2].wait()  # chaos SIGKILLed itself
        _wait_logged(procs[1], 60)
        assert "FAILED 0" in out0 and "WRONG_VALUE" not in out0, \
            out0[-3000:]
        assert "FINAL_EQUAL True" in out0, out0[-3000:]
        assert "rolling back" in out0, out0[-3000:]  # controller log
        # Rolled back before any interval reached server 2: the
        # committed prefix of the plan may have moved rows between the
        # two SURVIVORS, but 2 never owns anything.
        assert "OWNERS [0, 1]" in out0 or "OWNERS None" in out0, \
            out0[-3000:]

    def test_kill_migration_source_post_handoff_rolls_back(
            self, tmp_path):
        """SIGKILL the SOURCE at the instant it composes the final
        chunk (the handoff step itself): the chunk never reaches the
        destination, the controller declares the source dead and
        aborts the move at the destination (partial overlay dropped).
        The worker's reads of the dead source's rows fail RETRYABLY
        until it restarts with -rejoin and restores from its snapshot
        — after which every value is exact again. The reshard target
        [0, 2] makes the plan a SINGLE move ([8,16) from server 1 to
        server 2), so the kill deterministically lands on rank 1's
        handoff instant."""
        snap = tmp_path / "snaps"
        common = ["-snapshot_dir=" + str(snap),
                  "-snapshot_interval_s=0.5"]
        procs = _chaos_cluster(
            tmp_path,
            {0: common,
             1: common + ["-chaos_kill_on=shard_source_final"],
             2: common},
            window=30, target=[0, 2], presleep=3.0)
        # Wait for rank 1 to kill itself mid-handoff, then restart it
        # with -rejoin (the PR-6 machinery; its snapshot restores the
        # pre-kill state and the controller's re-register re-broadcast
        # re-anchors the map).
        procs[1].wait(timeout=260)
        restart = _CHAOS_COMMON.format(
            mf=str(tmp_path / "machines"),
            done=str(tmp_path / "worker.done"),
            extra_flags=common + ["-rejoin=true"])
        restart += _CHAOS_SERVER_TAIL
        replacement = _spawn(restart, str(tmp_path / "rank1b.log"),
                             extra_env={"MV_RANK": "1"})
        out0 = _wait_logged(procs[0], 420)
        _wait_logged(replacement, 120)
        _wait_logged(procs[2], 60)
        assert "WRONG_VALUE" not in out0, out0[-3000:]
        assert "FINAL_EQUAL True" in out0, out0[-3000:]
        assert "READS" in out0, out0[-3000:]
        # CONSISTENT epoch, either arm of the acceptance: ROLLED BACK
        # to the pre-move layout (owners [0,1] / frozen None), or —
        # when the replacement rejoins fast enough for the
        # controller's idempotent Begin-resend to re-drive the move
        # against its snapshot-restored shard — COMPLETED ([0,2]).
        # Both end element-wise exact; a half-moved layout would fail
        # here.
        assert ("OWNERS [0, 1]" in out0 or "OWNERS None" in out0
                or "OWNERS [0, 2]" in out0), out0[-3000:]
