"""Fault-tolerance suite: async snapshots + restore, crash detection and
rejoin, backup-worker straggler cutoff, diagnostic RPC timeouts.

The tentpole proof is ``test_kill_server_mid_epoch_word2vec``: a real
2-process TCP cluster trains PS word2vec, the server rank is SIGKILLed
mid-epoch and restarted from its periodic snapshot with ``-rejoin``,
and the final embeddings land within tolerance of an uninterrupted
baseline run — no worker hangs, every blocked RPC either retries
successfully or raises a diagnostic error within its timeout.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.runtime import actor as actors
from multiverso_tpu.runtime import device_lock
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.runtime.net import PeerLostError
from multiverso_tpu.runtime.server import _VectorClock, backup_worker_count
from multiverso_tpu.runtime.snapshot import SnapshotError
from multiverso_tpu.runtime.zoo import ClusterAborted
from multiverso_tpu.tables.table_interface import (RpcTimeoutError,
                                                  TableRequestError)
from multiverso_tpu.util.configure import set_flag

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _server_actor(zoo=None):
    zoo = zoo if zoo is not None else mv.current_zoo()
    return zoo._actors[actors.SERVER]


# ---------------------------------------------------------------------------
# Backup-worker vector clocks (pure host logic)
# ---------------------------------------------------------------------------

class TestVectorClockBackups:
    def test_strict_semantics_preserved_at_zero_backups(self):
        clock = _VectorClock(3, num_backup=0)
        assert not clock.update(0) and not clock.update(1)
        assert clock.update(2)  # all level
        assert clock.global_clock == 1.0

    def test_cutoff_advances_without_straggler(self):
        clock = _VectorClock(3, num_backup=1)
        # Workers 0 and 1 tick; worker 2 never does — the clock must
        # advance anyway (2 of 3 = n - num_backup have ticked).
        assert not clock.update(0)
        assert clock.update(1)
        assert clock.global_clock == 1.0
        assert not clock.update(0)
        assert clock.update(1)
        assert clock.global_clock == 2.0

    def test_late_straggler_tick_does_not_releveL(self):
        clock = _VectorClock(3, num_backup=1)
        clock.update(0)
        clock.update(1)  # global -> 1, straggler at 0
        assert not clock.update(2)  # late tick: no second advance
        assert clock.global_clock == 1.0

    def test_dead_worker_does_not_block_epoch(self):
        clock = _VectorClock(4, num_backup=1)
        for step in range(1, 6):
            for w in (0, 1):
                clock.update(w)
            leveled = clock.update(3)  # worker 2 is dead
            assert leveled, step
            assert clock.global_clock == float(step)

    def test_retired_workers_never_hold_back(self):
        # Worker 0 retired (+inf, sorts fastest): with 1 backup, worker
        # 1's tick alone levels the round — worker 2 is the skipped
        # straggler.
        clock = _VectorClock(3, num_backup=1)
        clock.finish_train(0)
        assert clock.update(1)
        assert clock.global_clock == 1.0

    def test_backup_count_parsing(self):
        set_flag("backup_worker_ratio", 20.0)  # 'set 20 means 20%'
        assert backup_worker_count(10) == 2
        set_flag("backup_worker_ratio", 0.2)   # fractional form
        assert backup_worker_count(10) == 2
        set_flag("backup_worker_ratio", 90.0)  # clamped: 1 must gate
        assert backup_worker_count(2) == 1
        set_flag("backup_worker_ratio", 0.0)
        assert backup_worker_count(10) == 0
        set_flag("backup_worker_ratio", 0.4)   # never on a lone worker
        assert backup_worker_count(1) == 0


def test_backup_workers_cut_straggler_epoch():
    """Acceptance: backup_worker_ratio > 0 measurably cuts the fast
    workers' epoch wall-clock under a seeded straggler, and every add
    still lands (vector-clock consistency)."""
    iters, straggle = 3, 0.4

    def run(ratio):
        times = [None] * 3
        sums = [None] * 3

        def body(rank):
            table = mv.create_kv_table()
            start = time.monotonic()
            for _ in range(iters):
                if rank == 2:
                    time.sleep(straggle)  # the seeded straggler
                table.add([0], [1.0])
                table.get([0])
            times[rank] = time.monotonic() - start
            mv.barrier()  # straggler included: all adds issued+acked
            sums[rank] = table.get([0])[0]
            return None

        cluster = LocalCluster(
            3, argv=["-sync=true", f"-backup_worker_ratio={ratio}"])
        cluster.run(body)
        return times, sums

    strict_times, strict_sums = run(0.0)
    cutoff_times, cutoff_sums = run(0.34)
    # All adds applied in both modes, BSP final state identical.
    assert all(s == pytest.approx(3 * iters) for s in strict_sums)
    assert all(s == pytest.approx(3 * iters) for s in cutoff_sums)
    # Strict BSP makes the fast workers pay the straggler's sleeps;
    # the cutoff must free them (generous margins for CI scheduling).
    fast_strict = min(strict_times[0], strict_times[1])
    fast_cutoff = min(cutoff_times[0], cutoff_times[1])
    assert fast_strict > iters * straggle * 0.6, strict_times
    assert fast_cutoff < fast_strict * 0.6, (strict_times, cutoff_times)


def test_bsp_results_unchanged_without_straggler():
    """ratio > 0 with no straggler injected: final state equals strict
    BSP (all adds commute to the same sum)."""
    def body(rank):
        table = mv.create_kv_table()
        for _ in range(4):
            table.add([rank], [float(rank + 1)])
            table.get([0, 1])
        mv.barrier()
        return table.get([0, 1])

    strict = LocalCluster(2, argv=["-sync=true"]).run(body)
    cutoff = LocalCluster(
        2, argv=["-sync=true", "-backup_worker_ratio=0.5"]).run(body)
    assert strict == cutoff
    assert strict[0][0] == pytest.approx(4.0)
    assert strict[0][1] == pytest.approx(8.0)


# ---------------------------------------------------------------------------
# Async snapshots + rejoin restore (in-process)
# ---------------------------------------------------------------------------

class TestSnapshots:
    def test_snapshot_roundtrip_and_rejoin_restore(self, tmp_path):
        snapdir = str(tmp_path / "snaps")
        mv.init([f"-snapshot_dir={snapdir}"])
        arr = mv.create_array_table(24)
        kv = mv.create_kv_table()
        arr.add(np.arange(24, dtype=np.float32))
        kv.add([3], [7.5])
        manager = _server_actor()._snapshots
        seq = manager.snapshot_once()
        assert seq == 1
        arr.add(np.ones(24, np.float32))  # post-snapshot add: not in cut
        versions = [t.version for t in mv.current_zoo().server_tables]
        mv.shutdown()

        mv.init([f"-snapshot_dir={snapdir}", "-rejoin=true"])
        arr2 = mv.create_array_table(24)
        kv2 = mv.create_kv_table()
        manager2 = _server_actor()._snapshots
        assert manager2.tables_restored == 2
        np.testing.assert_array_equal(arr2.get(),
                                      np.arange(24, dtype=np.float32))
        assert kv2.get([3])[3] == pytest.approx(7.5)
        # Versions restored to the SNAPSHOT's cut, not the later head.
        restored = [t.version for t in mv.current_zoo().server_tables]
        assert restored[0] == versions[0] - 1
        mv.shutdown()
        set_flag("rejoin", False)

    def test_manifest_is_internally_consistent(self, tmp_path):
        snapdir = str(tmp_path / "snaps")
        mv.init([f"-snapshot_dir={snapdir}"])
        arr = mv.create_array_table(8)
        manager = _server_actor()._snapshots
        arr.add(np.ones(8, np.float32))
        manager.snapshot_once()
        arr.add(np.ones(8, np.float32))
        manager.snapshot_once()
        manifest = json.loads(
            (tmp_path / "snaps" / "rank0" / "manifest.json").read_text())
        seqs = {e["seq"] for e in manifest["tables"].values()}
        assert seqs == {2}
        for entry in manifest["tables"].values():
            f = tmp_path / "snaps" / "rank0" / entry["file"]
            assert f.stat().st_size == entry["bytes"]
        mv.shutdown()

    def test_torn_snapshot_payload_refuses_loudly(self, tmp_path):
        snapdir = str(tmp_path / "snaps")
        mv.init([f"-snapshot_dir={snapdir}"])
        arr = mv.create_array_table(8)
        arr.add(np.ones(8, np.float32))
        _server_actor()._snapshots.snapshot_once()
        mv.shutdown()
        # Tear the payload: manifest still names the full size.
        rank_dir = tmp_path / "snaps" / "rank0"
        snap = next(p for p in rank_dir.iterdir()
                    if p.name.endswith(".snap"))
        snap.write_bytes(snap.read_bytes()[:-8])
        mv.init([f"-snapshot_dir={snapdir}", "-rejoin=true"])
        try:
            with pytest.raises(SnapshotError, match="torn"):
                mv.create_array_table(8)
        finally:
            mv.current_zoo().abort()  # table half-created: skip barrier
            mv.shutdown()
            set_flag("rejoin", False)

    def test_rejoin_survives_slow_table_recreation(self, tmp_path):
        """Regression: a rejoining server's OWN periodic snapshotter
        must not clobber the restore state while the application is
        still re-creating tables. Before the _rounds_blocked guard,
        early empty rounds overwrote the manifest and (two rounds
        later) garbage-collected the payload the pending restore still
        pointed at — any app whose table re-creation took more than
        two intervals lost its restore to a 'torn payload' error."""
        snapdir = str(tmp_path / "snaps")
        mv.init([f"-snapshot_dir={snapdir}"])
        arr = mv.create_array_table(16)
        arr.add(np.arange(16, dtype=np.float32))
        _server_actor()._snapshots.snapshot_once()
        mv.shutdown()

        mv.init([f"-snapshot_dir={snapdir}", "-rejoin=true",
                 "-snapshot_interval_s=0.05"])
        manager = _server_actor()._snapshots
        # Simulate a slow re-creating application: many intervals pass
        # before the first table registers. Rounds must hold off (and
        # the restore payload survive), not commit empty manifests.
        time.sleep(0.5)
        assert manager.rounds_written == 0
        arr2 = mv.create_array_table(16)
        assert manager.tables_restored == 1
        np.testing.assert_array_equal(arr2.get(),
                                      np.arange(16, dtype=np.float32))
        # With the table restored and ready, periodic rounds resume.
        deadline = time.monotonic() + 10
        while manager.rounds_written < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert manager.rounds_written >= 1
        mv.shutdown()
        set_flag("rejoin", False)

    def test_table_created_after_cut_starts_fresh_on_rejoin(self, tmp_path):
        """A table the manifest does not cover — created AFTER the last
        snapshot round committed — must start fresh on rejoin (its
        post-snapshot updates are lost; that IS the cut's point in
        time), not raise SnapshotError into the application's table
        constructor and kill the very rejoin the feature exists for."""
        snapdir = str(tmp_path / "snaps")
        mv.init([f"-snapshot_dir={snapdir}"])
        arr = mv.create_array_table(8)
        arr.add(np.arange(8, dtype=np.float32))
        _server_actor()._snapshots.snapshot_once()
        kv = mv.create_kv_table()  # after the cut: no manifest entry
        kv.add([1], [2.0])
        mv.shutdown()

        mv.init([f"-snapshot_dir={snapdir}", "-rejoin=true"])
        arr2 = mv.create_array_table(8)
        kv2 = mv.create_kv_table()  # must not raise
        assert _server_actor()._snapshots.tables_restored == 1
        np.testing.assert_array_equal(arr2.get(),
                                      np.arange(8, dtype=np.float32))
        # Fresh start: the pre-crash post-snapshot KV add is gone.
        assert kv2.get([1])[1] == pytest.approx(0.0)
        mv.shutdown()
        set_flag("rejoin", False)

    def test_periodic_snapshots_run_while_serving(self, tmp_path):
        """The snapshotter thread overlaps live Get/Add traffic: rounds
        advance while the table keeps serving exact values."""
        snapdir = str(tmp_path / "snaps")
        mv.init([f"-snapshot_dir={snapdir}", "-snapshot_interval_s=0.05"])
        table = mv.create_array_table(512)
        manager = _server_actor()._snapshots
        for i in range(30):
            table.add(np.ones(512, np.float32))
            out = table.get()
            assert out[0] == pytest.approx(i + 1.0)
            time.sleep(0.01)
        deadline = time.monotonic() + 10
        while manager.rounds_written < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert manager.rounds_written >= 2
        mv.shutdown()


# ---------------------------------------------------------------------------
# zoo.abort() / dead-rank semantics + RPC timeout diagnostics
# ---------------------------------------------------------------------------

def test_abort_mid_barrier_wakes_all_blocked_peers():
    """Pins the zoo.abort() claim: a blocked barrier() raises
    ClusterAborted promptly when the zoo is aborted from another
    thread, for every rank aborted — no hang, no mispair."""
    zoos = {}
    woke = {}

    def body(rank):
        zoos[rank] = mv.current_zoo()
        if rank == 1:
            time.sleep(0.4)  # let ranks 0 (and its barrier) block first
            for z in zoos.values():
                z.abort()
            return "aborter"
        start = time.monotonic()
        with pytest.raises(ClusterAborted):
            mv.barrier()  # rank 1 never joins
        woke[rank] = time.monotonic() - start
        raise ClusterAborted("woken as expected")

    cluster = LocalCluster(2)
    with pytest.raises(ClusterAborted):
        cluster.run(body)
    assert woke[0] < 10.0  # woken by abort, not by a join timeout


def test_checkpoint_roundtrip_all_table_types_two_ranks(tmp_path):
    """save/load_checkpoint round-trips all four table types (array,
    dense matrix, sparse matrix, kv) under LocalCluster(n=2) — every
    rank persists and restores its own shards."""
    prefix = str(tmp_path / "ckpt")

    def body(rank):
        arr = mv.create_array_table(12)
        dense = mv.create_matrix_table(8, 3)
        sparse = mv.create_matrix_table(8, 3, is_sparse=True)
        kv = mv.create_kv_table()
        if rank == 0:
            arr.add(np.arange(12, dtype=np.float32))
            dense.add_rows(np.array([1, 7], np.int32),
                           np.ones((2, 3), np.float32))
            sparse.add_rows(np.array([2], np.int32),
                            np.full((1, 3), 2.0, np.float32))
            kv.add([5], [1.25])
        mv.barrier()
        from multiverso_tpu.io import load_checkpoint, save_checkpoint
        assert save_checkpoint(prefix) == 4
        mv.barrier()
        if rank == 0:  # wipe, then restore everywhere
            arr.add(np.ones(12, np.float32))
            dense.add_rows(np.array([1], np.int32),
                           np.full((1, 3), 9.0, np.float32))
        mv.barrier()
        assert load_checkpoint(prefix) == 4
        mv.barrier()
        np.testing.assert_array_equal(arr.get(),
                                      np.arange(12, dtype=np.float32))
        out = dense.get()
        assert np.allclose(out[1], 1.0) and np.allclose(out[7], 1.0)
        assert np.allclose(sparse.get()[2], 2.0)
        assert kv.get([5])[5] == pytest.approx(1.25)
        mv.barrier()
        return True

    assert LocalCluster(2).run(body) == [True, True]


def test_rpc_timeout_raises_diagnostic_naming_peer():
    """-rpc_timeout_s: a request whose replies never arrive raises
    RpcTimeoutError naming the table, msg_id and pending peers instead
    of blocking forever."""
    mv.init(["-rpc_timeout_s=0.5"])
    table = mv.create_array_table(16)
    table.add(np.ones(16, np.float32))
    # Wedge the server actor: its table logic serializes on the device
    # table lock, which the test thread holds — no reply can form.
    # Server._lock_for only routes through TABLE_LOCK while multi-zoo
    # serialization is ACTIVE (the ISSUE-7 single-process relaxation),
    # so activate it for the wedge window.
    device_lock.enable()
    # Precondition, not a formality: enable() is a no-op on a
    # single-device process (ISSUE-7 relaxation), where TABLE_LOCK
    # would not wedge the server and the raises below would fail —
    # this test requires the conftest 8-virtual-device mesh.
    assert device_lock.active()
    device_lock.TABLE_LOCK.acquire()
    try:
        with pytest.raises(RpcTimeoutError) as err:
            table.get()
    finally:
        device_lock.TABLE_LOCK.release()
        device_lock.disable()
    text = str(err.value)
    assert "table 0" in text and "peers pending" in text and "0" in text
    # The wedged reply lands late and harmlessly; serving resumes.
    out = table.get()
    assert out[0] == pytest.approx(1.0)
    mv.shutdown()


def test_peer_lost_marked_errors_raise_typed_retryable():
    mv.init([])
    table = mv.create_array_table(8)
    from multiverso_tpu.core.message import PEER_LOST_MARK
    msg_id = table._new_request()
    table.fail(msg_id, f"{PEER_LOST_MARK} rank 9 died", count=True)
    with pytest.raises(PeerLostError):
        table.wait(msg_id)
    mv.shutdown()


def test_retrying_wait_reissues_until_success():
    mv.init(["-rpc_retry_max=3", "-rpc_backoff_ms=5"])
    table = mv.create_array_table(8)
    from multiverso_tpu.core.message import PEER_LOST_MARK
    attempts = []

    def flaky_issue():
        msg_id = table._new_request()
        attempts.append(msg_id)
        if len(attempts) < 3:
            table.fail(msg_id, f"{PEER_LOST_MARK} transient", count=True)
        else:
            table.notify(msg_id)
        return msg_id

    table.retrying_wait(flaky_issue)
    assert len(attempts) == 3
    mv.shutdown()


def test_sync_mode_never_reissues_requests():
    """BSP regression: the sync servers count ONE request per worker
    per step on their vector clocks, so retrying_wait must never
    re-issue in sync mode — a retried request would double-tick the
    surviving servers' clocks and permanently skew the worker ahead."""
    mv.init(["-sync=true", "-rpc_retry_max=3", "-rpc_backoff_ms=5"])
    table = mv.create_array_table(8)
    from multiverso_tpu.core.message import PEER_LOST_MARK
    attempts = []

    def lost_issue():
        msg_id = table._new_request()
        attempts.append(msg_id)
        table.fail(msg_id, f"{PEER_LOST_MARK} rank 9 died", count=True)
        return msg_id

    with pytest.raises(PeerLostError):
        table.retrying_wait(lost_issue)
    assert len(attempts) == 1  # issued exactly once: no sync re-issue
    mv.shutdown()


def test_rpc_timeout_reaps_abandoned_request_state():
    """A timed-out request is ABANDONED: its waiter, recorded error,
    and the worker's in-flight entries must be reaped, or every
    timeout (the flag's target is a peer that never replies) leaks one
    of each and pollutes later pending_peers diagnostics."""
    mv.init(["-rpc_timeout_s=0.3"])
    table = mv.create_array_table(16)
    table.add(np.ones(16, np.float32))
    worker = mv.current_zoo()._actors[actors.WORKER]
    device_lock.enable()  # see test above: make the wedge lock live
    assert device_lock.active()
    device_lock.TABLE_LOCK.acquire()
    try:
        with pytest.raises(RpcTimeoutError):
            table.get()
        assert not table._waitings
        assert not table._errors
        assert not worker._inflight
    finally:
        device_lock.TABLE_LOCK.release()
        device_lock.disable()
    mv.shutdown()


# ---------------------------------------------------------------------------
# Transport-level peer death (unit: a dead peer must fail loudly)
# ---------------------------------------------------------------------------

def test_dead_peer_wakes_senders_with_peer_lost():
    """Frames queued toward an unreachable endpoint die with a typed
    retryable PeerLostError once the nonblocking connect's retry
    deadline expires — queued flushers wake, later submits fail fast,
    and no thread is left parked toward the corpse."""
    from multiverso_tpu.core.message import Blob, Message, MsgType
    from multiverso_tpu.runtime.tcp import TcpNet
    from multiverso_tpu.util.configure import get_flag
    from multiverso_tpu.util.net_util import free_listen_port

    saved = get_flag("connect_timeout_s")
    set_flag("connect_timeout_s", 0.4)
    # Rank 1's endpoint is a port nobody listens on: every dial gets
    # ECONNREFUSED and the event loop retries with backoff until the
    # connect deadline kills the peer.
    eps = [f"127.0.0.1:{free_listen_port()}",
           f"127.0.0.1:{free_listen_port()}"]
    net = TcpNet(0, eps)
    try:
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Add)
        msg.push(Blob(np.zeros(16, np.float32)))
        net.send_async(msg)
        with pytest.raises(PeerLostError, match="rank 1"):
            net.flush_sends(1, timeout=10.0)
        # Death retires the peer machine: nothing queued toward the
        # corpse, and the NEXT send starts a fresh connect cycle (the
        # rejoin path) that dies the same loud way while the endpoint
        # stays unreachable.
        assert net.queue_depths().get(1, 0) == 0
        net.send_async(msg)
        with pytest.raises(PeerLostError, match="rank 1"):
            net.flush_sends(1, timeout=10.0)
    finally:
        net.finalize()
        set_flag("connect_timeout_s", saved)


# ---------------------------------------------------------------------------
# Controller-driven liveness (heartbeats)
# ---------------------------------------------------------------------------

def test_heartbeat_monitor_declares_silent_rank_dead():
    dead_seen = {}

    def body(rank):
        zoo = mv.current_zoo()
        if rank == 1:
            # Fall silent: stop heartbeating (the process is "wedged").
            zoo._heartbeat.stop()
            time.sleep(2.2)
        else:
            deadline = time.monotonic() + 8
            while 1 not in zoo._dead_peers \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            dead_seen[rank] = set(zoo._dead_peers)
        mv.barrier()  # rank 1 is actually alive: cluster still works
        return True

    cluster = LocalCluster(
        2, argv=["-heartbeat_interval_s=0.1", "-heartbeat_timeout_s=0.5",
                 "-rpc_retry_max=1"])
    assert cluster.run(body) == [True, True]
    assert dead_seen[0] == {1}


def test_barrier_fails_after_rejoin_grace():
    """A declared-dead rank that never rejoins must not hang barriers
    forever under containment: past -rejoin_grace_s the controller
    fails the parked round with a retryable PeerLostError, and a
    LATER barrier (once the rank is back in touch) still completes."""
    raised = {}
    resume = threading.Event()

    def body(rank):
        zoo = mv.current_zoo()
        if rank == 1:
            # Fall silent past heartbeat timeout + grace, never enter
            # the first barrier.
            zoo._heartbeat.stop()
            assert resume.wait(20), "rank 0 never saw the barrier fail"
        else:
            with pytest.raises(PeerLostError, match="rejoin_grace"):
                mv.barrier()
            raised[0] = True
            resume.set()
            # Let rank 1's entry land first: it refreshes the rank's
            # liveness record, so the round cannot be grace-failed
            # again while rank 0's entry would otherwise park alone.
            time.sleep(0.3)
        mv.barrier()
        return True

    cluster = LocalCluster(
        2, argv=["-heartbeat_interval_s=0.1", "-heartbeat_timeout_s=0.4",
                 "-rejoin_grace_s=0.4", "-rpc_retry_max=1"])
    assert cluster.run(body) == [True, True]
    assert raised.get(0)


# ---------------------------------------------------------------------------
# THE tentpole proof: kill a server mid-epoch, restart from snapshot
# ---------------------------------------------------------------------------

_PRELUDE = """
import os, sys, time
import faulthandler
faulthandler.dump_traceback_later(280, exit=True)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import multiverso_tpu as mv
"""


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(body, log_path, extra_env=None):
    """Launch a cluster process with stdout+stderr to a FILE, not a
    pipe: a retry storm (NACK/backoff log lines) can exceed the 64KB
    pipe buffer long before the test drains it, blocking the subprocess
    on a print — which reads as a cluster hang."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=REPO)
    env.update(extra_env or {})
    out = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _PRELUDE.format(repo=REPO) + body],
        env=env, stdout=out, stderr=subprocess.STDOUT, text=True)
    out.close()  # the subprocess holds its own descriptor
    proc.log_path = log_path
    return proc


def _wait_logged(proc, timeout):
    """communicate() twin for file-logged processes: wait (killing on
    timeout — the caller's returncode assert then fails loudly), then
    read the log back."""
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    with open(proc.log_path) as f:
        return f.read()


def _write_corpus(path, lines=160, seed=0):
    rng = np.random.default_rng(seed)
    topics = [[f"a{i}" for i in range(8)], [f"b{i}" for i in range(8)]]
    with open(path, "w") as f:
        for _ in range(lines):
            topic = topics[rng.integers(0, 2)]
            f.write(" ".join(rng.choice(topic, size=10)) + "\n")


_W2V_COMMON = """
from multiverso_tpu.models.wordembedding import (Dictionary, PSWord2Vec,
                                                 Word2VecConfig,
                                                 iter_pair_batches)
corpus = {corpus!r}
d = Dictionary.build(corpus, min_count=1)
config = Word2VecConfig(embedding_size=8, window=3, epochs=3,
                        init_learning_rate=0.02, batch_size=256,
                        sample=0, use_ps=True, seed=3)
# epochs=3 matches the 3 passes the training loop below makes: the lr
# schedule decays over epochs*total_count words — a shorter schedule
# would zero the lr mid-run, leaving a restored-from-snapshot server
# no usable lr window to retrain the lost delta in.
"""

_W2V_WORKER = _W2V_COMMON + """
from multiverso_tpu.runtime.net import PeerLostError
from multiverso_tpu.tables.table_interface import TableRequestError
mv.init(["-machine_file={mf}", "-rank=0", "-ps_role=worker",
         "-rpc_retry_max=12", "-rpc_backoff_ms=150", "-rpc_timeout_s=60",
         "-connect_timeout_s=20"])
model = PSWord2Vec(config, d)
losses = []
batches = list(iter_pair_batches(d, corpus, batch_size=256, window=3,
                                 subsample=0, seed=0))
step = 0
for epoch in range(3):
    for batch in batches:
        for attempt in range(40):
            try:
                losses.append(model.train_batch(batch))
                break
            except (PeerLostError, TableRequestError) as exc:
                # A push ack died with the server: the delta may or may
                # not have applied (at-least-once) — drop the pending
                # acks and retrain the batch once the server is back.
                model._pending_pushes.clear()
                print("RETRY_BATCH", step, type(exc).__name__,
                      flush=True)
                time.sleep(0.3)
        else:
            raise SystemExit(f"batch {{step}} never trained")
        step += 1
        with open({progress!r}, "w") as f:
            f.write(str(step))
        if step == {kill_batch}:
            # Rendezvous with the harness: pause here until it has seen
            # a FRESH snapshot round land (so the kill loses at most the
            # in-flight round) and is about to SIGKILL the server —
            # without the gate, a slow snapshot round under full-suite
            # load lets training finish and rank 0 (the controller)
            # exit before the kill, stranding the replacement's rejoin
            # registration.
            gate_deadline = time.monotonic() + 120
            while not os.path.exists({gate!r}):
                if time.monotonic() > gate_deadline:
                    raise SystemExit("kill gate never opened")
                time.sleep(0.05)
np.save({outfile!r}, model.embeddings)
half = max(len(losses) // 2, 1)
print("LOSS_EARLY", float(np.mean(losses[:half])), flush=True)
print("LOSS_LATE", float(np.mean(losses[half:])), flush=True)
mv.shutdown()
print("TRAIN_OK", flush=True)
"""

_W2V_SERVER = _W2V_COMMON + """
mv.init(["-machine_file={mf}", "-rank=1", "-ps_role=server",
         "-rpc_retry_max=12", "-connect_timeout_s=20"{extra}])
model = PSWord2Vec(config, d)
print("SERVER_READY", flush=True)
mv.shutdown()  # the shutdown barrier is the rendezvous with the worker
print("SERVER_EXIT", flush=True)
"""


def _run_w2v_cluster(tmp_path, tag, kill_at=None, timeout=300):
    """One 2-process PS word2vec run; with ``kill_at`` the server rank
    is SIGKILLed once the worker passes that batch and a replacement is
    started from the snapshot with -rejoin. Returns (embeddings,
    worker stdout)."""
    ports = [_free_port(), _free_port()]
    mf = tmp_path / f"machines_{tag}"
    mf.write_text("".join(f"127.0.0.1:{p}\n" for p in ports))
    corpus = tmp_path / "corpus.txt"
    if not corpus.exists():
        _write_corpus(corpus)
    outfile = str(tmp_path / f"emb_{tag}.npy")
    progress = str(tmp_path / f"progress_{tag}")
    gate = str(tmp_path / f"gate_{tag}")
    snapdir = str(tmp_path / f"snaps_{tag}")
    snap_flags = (f', "-snapshot_dir={snapdir}", '
                  f'"-snapshot_interval_s=0.15"')
    worker = _spawn(_W2V_WORKER.format(corpus=str(corpus), mf=str(mf),
                                       progress=progress, gate=gate,
                                       kill_batch=(-1 if kill_at is None
                                                   else kill_at),
                                       outfile=outfile),
                    str(tmp_path / f"worker_{tag}.log"))
    server = _spawn(_W2V_SERVER.format(corpus=str(corpus), mf=str(mf),
                                       extra=snap_flags),
                    str(tmp_path / f"server_{tag}.log"))
    replacement = None
    procs = [worker, server]
    try:
        if kill_at is not None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    if int(open(progress).read() or -1) >= kill_at:
                        break
                except (OSError, ValueError):
                    pass
                if worker.poll() is not None:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("worker never reached the kill batch")
            # Kill right AFTER a fresh snapshot round lands: the
            # restore then covers (nearly) the pre-kill state and the
            # lost window is the one in-flight round. The contract
            # under test is crash RECOVERY — how much a sparse
            # snapshot cadence loses is a tuning knob, not the test.
            manifest = os.path.join(snapdir, "rank1", "manifest.json")

            def _seq():
                try:
                    with open(manifest) as f:
                        return int(json.load(f)["seq"])
                except (OSError, ValueError, KeyError):
                    return 0

            fresh_from = _seq()
            fresh_deadline = time.monotonic() + 60
            while (_seq() <= fresh_from
                   and time.monotonic() < fresh_deadline):
                time.sleep(0.03)
            # Open the worker's gate, give it a beat to resume training
            # against the live server, then kill: the SIGKILL lands
            # mid-traffic, deterministically BEFORE training can finish
            # (the worker was parked until this moment).
            with open(gate, "w") as f:
                f.write("go")
            time.sleep(0.25)
            server.send_signal(signal.SIGKILL)
            time.sleep(0.6)
            replacement = _spawn(_W2V_SERVER.format(
                corpus=str(corpus), mf=str(mf),
                extra=snap_flags + ', "-rejoin=true"'),
                str(tmp_path / f"server_{tag}_rejoin.log"))
            procs.append(replacement)
        out = _wait_logged(worker, timeout)
        assert worker.returncode == 0, out[-3000:]
        assert "TRAIN_OK" in out, out[-3000:]
        final_server = replacement if replacement is not None else server
        sout = _wait_logged(final_server, 60)
        assert final_server.returncode == 0, sout[-3000:]
        assert "SERVER_EXIT" in sout, sout[-3000:]
        return np.load(outfile), out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def test_kill_server_mid_epoch_word2vec(tmp_path):
    baseline, base_out = _run_w2v_cluster(tmp_path, "base")
    killed, kill_out = _run_w2v_cluster(tmp_path, "kill", kill_at=6)
    # The kill really happened and was survived through retries.
    assert "RETRY_BATCH" in kill_out, kill_out[-3000:]
    assert np.isfinite(killed).all()
    # Training converged in both runs...
    for out in (base_out, kill_out):
        early = float(out.split("LOSS_EARLY ")[1].split()[0])
        late = float(out.split("LOSS_LATE ")[1].split()[0])
        assert late < early, (early, late)
    # ...and the interrupted run's embeddings land within tolerance of
    # the uninterrupted baseline (the crash window loses at most the
    # since-last-snapshot adds; retried pushes are at-least-once).
    rel = np.linalg.norm(killed - baseline) / np.linalg.norm(baseline)
    assert rel < 0.5, rel


# ---------------------------------------------------------------------------
# Slow extras: chaos smoke + snapshot latency bound
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_random_kill_propagates_cleanly(tmp_path):
    """Chaos smoke: SIGKILL one non-controller rank of a 3-process
    cluster mid-run (no retry flags: the pre-fault-tolerance abort
    path); every survivor must EXIT with a clean error promptly — not
    hang."""
    ports = [_free_port() for _ in range(3)]
    mf = tmp_path / "machines"
    mf.write_text("".join(f"127.0.0.1:{p}\n" for p in ports))
    body = """
from multiverso_tpu.runtime.zoo import ClusterAborted
from multiverso_tpu.tables.table_interface import TableRequestError
rank = int(os.environ["MV_RANK"])
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
table = mv.create_array_table(64)
try:
    for i in range(2000):
        table.add(np.ones(64, np.float32))
        table.get()
        time.sleep(0.01)
    print("FINISHED_ALL", flush=True)
except (ClusterAborted, TableRequestError, Exception) as exc:
    print("CLEAN_ERROR", type(exc).__name__, flush=True)
""".replace("{mf}", str(mf))
    rng = np.random.default_rng()
    victim = int(rng.integers(1, 3))
    procs = [_spawn(body, str(tmp_path / f"rank{r}.log"),
                    extra_env={"MV_RANK": str(r)})
             for r in range(3)]
    time.sleep(25)  # well into the table loop (jit warmup included)
    procs[victim].send_signal(signal.SIGKILL)
    for r, p in enumerate(procs):
        if r == victim:
            p.wait()
            continue
        out = _wait_logged(p, 90)  # kills on expiry: the assert fails
        assert "CLEAN_ERROR" in out or "FINISHED_ALL" in out, \
            f"survivor rank {r} HUNG (or died dirty) after the kill:\n" \
            f"{out[-2000:]}"


@pytest.mark.slow
def test_liveness_survives_blocked_dispatch_kill_rejoin(tmp_path):
    """Regression: liveness frames — heartbeats, their REPLIES, and
    Dead_Peer notices — must leave the process via non-blocking direct
    net sends (send_async), never the communicator mailbox. On a
    combined controller+worker rank the single dispatch thread parks
    for up to -connect_timeout_s in a connect-retry toward a SIGKILLed
    server; a heartbeat (monitor->controller) or its reply
    (controller->monitor) queued behind it starves past
    -heartbeat_timeout_s, so healthy ranks get falsely declared dead /
    falsely conclude the controller died and abort — one crash
    cascading cluster-wide. Caught live by a verify drive (first the
    request path, then, once that was fixed, the reply path)."""
    ports = [_free_port() for _ in range(3)]
    mf = tmp_path / "machines"
    mf.write_text("".join(f"127.0.0.1:{p}\n" for p in ports))
    snapdir = str(tmp_path / "snaps")
    common = ('"-machine_file={mf}", "-rank=" + str(rank), '
              '"-rpc_retry_max=30", "-rpc_backoff_ms=100", '
              '"-rpc_timeout_s=60", "-connect_timeout_s=25", '
              '"-heartbeat_interval_s=0.2", '
              '"-heartbeat_timeout_s=2.0"').replace("{mf}", str(mf))
    worker = """
from multiverso_tpu.runtime.net import PeerLostError
from multiverso_tpu.tables.table_interface import TableRequestError
rank = int(os.environ["MV_RANK"])
mv.init([%s, "-ps_role=worker"])
arr = mv.create_array_table(32)
kv = mv.create_kv_table()
for i in range(120):
    for attempt in range(60):
        try:
            arr.add(np.ones(32, np.float32))
            kv.add([rank], [1.0])
            arr.get()
            kv.get([rank])
            break
        except (PeerLostError, TableRequestError):
            time.sleep(0.2)
    else:
        raise SystemExit("iteration %%d never succeeded" %% i)
    time.sleep(0.02)
mv.barrier()
mv.shutdown()
print("WORKER_EXIT_OK", flush=True)
""" % common
    server = """
rank = 1
extra = ["-rejoin=true"] if os.environ.get("MV_REJOIN") == "1" else []
mv.init([%s, "-ps_role=server", "-snapshot_dir=%s",
         "-snapshot_interval_s=0.3"] + extra)
arr = mv.create_array_table(32)
kv = mv.create_kv_table()
print("SERVER_READY", flush=True)
mv.barrier()
mv.shutdown()
print("SERVER_EXIT_OK", flush=True)
""" % (common, snapdir)
    logs = {n: str(tmp_path / f"{n}.log") for n in
            ("w0", "w2", "s1", "s1b")}
    w0 = _spawn(worker, logs["w0"], extra_env={"MV_RANK": "0"})
    s1 = _spawn(server, logs["s1"], extra_env={"MV_RANK": "1"})
    w2 = _spawn(worker, logs["w2"], extra_env={"MV_RANK": "2"})
    try:
        manifest = os.path.join(snapdir, "rank1", "manifest.json")
        deadline = time.monotonic() + 120
        while not os.path.exists(manifest):
            assert time.monotonic() < deadline, "no snapshot manifest"
            assert s1.poll() is None, _wait_logged(s1, 1)[-2000:]
            time.sleep(0.1)
        time.sleep(1.0)  # live traffic on top of a committed round
        s1.send_signal(signal.SIGKILL)
        s1.wait()
        # Dead window of 2x -heartbeat_timeout_s: with mailbox-queued
        # liveness frames, the workers (whose dispatch threads are
        # parked in connect-retry toward rank 1) get falsely declared
        # dead in here.
        time.sleep(4.0)
        s1b = _spawn(server, logs["s1b"],
                     extra_env={"MV_RANK": "1", "MV_REJOIN": "1"})
        out_w0 = _wait_logged(w0, 120)
        out_w2 = _wait_logged(w2, 120)
        out_s1b = _wait_logged(s1b, 60)
    finally:
        for p in (w0, w2, s1, locals().get("s1b")):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
    assert "WORKER_EXIT_OK" in out_w0, out_w0[-2500:]
    assert "WORKER_EXIT_OK" in out_w2, out_w2[-2500:]
    assert "SERVER_EXIT_OK" in out_s1b, out_s1b[-2500:]
    assert "restored table" in out_s1b, out_s1b[-2500:]
    # Only the killed rank may ever be declared dead.
    for name in ("w0", "w2", "s1b"):
        for line in open(logs[name]).read().splitlines():
            if "declaring it dead" in line:
                assert "rank 1 " in line, f"{name} FALSE DEATH: {line}"


@pytest.mark.slow
def test_snapshot_get_p99_within_bound(tmp_path):
    """Acceptance: Get p99 latency under periodic snapshotting stays
    within 1.2x of no-snapshot (the capture is O(1) under the lock;
    serialization runs off the actor thread)."""
    def measure(argv):
        mv.init(argv)
        table = mv.create_array_table(1 << 16)
        table.add(np.ones(1 << 16, np.float32))
        for _ in range(20):  # warmup
            table.get()
        lat = []
        for _ in range(300):
            t0 = time.perf_counter()
            table.get()
            lat.append(time.perf_counter() - t0)
        mv.shutdown()
        return float(np.percentile(lat, 99))

    snapdir = str(tmp_path / "snaps")
    ratios = []
    for _ in range(3):
        base = measure([])
        snap = measure([f"-snapshot_dir={snapdir}",
                        "-snapshot_interval_s=0.05"])
        ratios.append(snap / base)
        if min(ratios) < 1.2:
            break
    assert min(ratios) < 1.2, ratios
