"""Self-tests for the mvchk deterministic-schedule model checker
(tools/mvchk) — the dynamic half of the PR-20 concurrency gate.

The checker is regression-protected the same way the mvlint fixtures
are: every good spec must keep passing bounded exploration, and the
known-bad pre-PR-19 event-loop ordering must keep being REFUTED with
a readable counterexample — a checker that blesses it has gone
vacuous and these tests fail loudly.
"""

from __future__ import annotations

import subprocess
import sys

import pytest

from multiverso_tpu.util import lock_witness
from tools.mvchk import (ALL_SPECS, SPECS_BY_NAME, Deadlock, explore,
                         format_trace, run_once, soak)
from tools.mvchk.core import MLock, Scheduler, SchedVar


class TestScheduler:
    def test_single_task_runs_to_completion(self):
        hits = []

        def body(sched):
            def t():
                hits.append(sched.current_task().name)
            sched.spawn("solo", t)

        from tools.mvchk.core import Spec
        out = run_once(Spec("solo", "one task", body))
        assert out.ok, out.error
        assert hits == ["solo"]

    def test_deadlock_names_the_blocked_task(self):
        def body(sched):
            def t():
                sched.yield_point("park forever", pred=lambda: False)
            sched.spawn("blocked", t)

        from tools.mvchk.core import Spec
        out = run_once(Spec("dl", "deadlock", body))
        assert not out.ok
        assert isinstance(out.error, Deadlock)
        assert "blocked" in str(out.error)
        assert "park forever" in str(out.error)

    def test_virtual_time_expires_timeouts(self):
        """A timed wait on a dead condition expires via vtime — no
        wall-clock sleep, so the run is instant."""
        results = []

        def body(sched):
            def t():
                timed_out = sched.yield_point(
                    "park", pred=lambda: False, timeout_ok=True)
                results.append(timed_out)
            sched.spawn("sleeper", t)

        from tools.mvchk.core import Spec
        out = run_once(Spec("vt", "vtime", body))
        assert out.ok, out.error
        assert results == [True]

    def test_no_thread_model_residue_after_run(self):
        """run_once installs the model facade around setup+run and must
        clear it even though specs construct real MtQueue/Waiter
        objects: a leaked model would poison every later test."""
        out = run_once(SPECS_BY_NAME["mtqueue-exit-drain"])
        assert out.ok, out.error
        assert lock_witness._THREAD_MODEL is None
        # Fresh primitives bind real threading locks again.
        from multiverso_tpu.util.mt_queue import MtQueue
        q = MtQueue("residue-probe")
        assert not isinstance(q._mutex, MLock)
        q.exit()


class TestSpecs:
    @pytest.mark.parametrize(
        "name", [s.name for s in ALL_SPECS if not s.expect_fail])
    def test_good_spec_passes_systematic(self, name):
        result = explore(SPECS_BY_NAME[name], max_schedules=600)
        if result.refuted:
            pytest.fail(f"{name} refuted:\n"
                        f"{format_trace(result.counterexample)}")
        assert result.schedules >= 1

    def test_known_bad_is_refuted_with_readable_trace(self):
        """THE self-check: the explorer must reproduce the pre-PR-19
        lost wakeup (stopper reads a stale latch, skips the wake byte;
        the loop re-arms and parks on an empty pipe)."""
        result = explore(SPECS_BY_NAME["event-loop-pre-pr19"])
        assert result.refuted, (
            "checker lost the known-bad counterexample")
        trace = format_trace(result.counterexample)
        assert "Deadlock" in trace
        assert "select(wakepipe)" in trace
        # The schedule itself is recorded, so the refutation replays.
        assert result.counterexample.schedule

    def test_counterexample_replays_deterministically(self):
        result = explore(SPECS_BY_NAME["event-loop-pre-pr19"])
        sched = result.counterexample.schedule
        replay = run_once(SPECS_BY_NAME["event-loop-pre-pr19"],
                          prefix=sched)
        assert not replay.ok
        assert isinstance(replay.error, Deadlock)

    def test_good_event_loop_survives_soak(self):
        result = soak(SPECS_BY_NAME["event-loop-wake"], runs=25,
                      seed=1234)
        if result.refuted:
            pytest.fail(format_trace(result.counterexample))

    def test_soak_finds_the_known_bad_eventually(self):
        # Random search is weaker than systematic but the window is
        # wide enough that a modest soak still lands in it.
        result = soak(SPECS_BY_NAME["event-loop-pre-pr19"], runs=200,
                      seed=99)
        assert result.refuted


class TestCli:
    def test_module_entrypoint_known_bad_gate(self):
        """`python -m tools.mvchk` is the CI gate: exit 0 means every
        good spec passed AND the known-bad spec was refuted."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mvchk",
             "--spec", "mtqueue-exit-drain",
             "--spec", "event-loop-pre-pr19"],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "refuted as required" in proc.stdout
        assert "step" in proc.stdout  # the readable trace printed

    def test_module_entrypoint_lists_specs(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mvchk", "--list"],
            capture_output=True, text=True)
        assert proc.returncode == 0
        assert "event-loop-pre-pr19" in proc.stdout
        assert "[known-bad]" in proc.stdout


@pytest.mark.slow
class TestSoakSlow:
    def test_long_soak_all_good_specs(self):
        for spec in ALL_SPECS:
            if spec.expect_fail:
                continue
            result = soak(spec, runs=300, seed=20260807)
            if result.refuted:
                pytest.fail(f"{spec.name}:\n"
                            f"{format_trace(result.counterexample)}")
