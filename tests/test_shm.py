"""Shared-memory transport suite (runtime/shm.py, docs/MEMORY.md
"Below the socket").

In-process pairs drive two real TcpNet endpoints wrapped in ShmNet
through the full negotiate/announce/attach cycle; subprocess clusters
prove mixed-transport interop and lifecycle hygiene. A `/dev/shm`
entry — or a resource_tracker warning on stderr — surviving any test
here is a failure, not a flake.
"""

import hashlib
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import Message, MsgType
from multiverso_tpu.runtime import shm
from multiverso_tpu.runtime.shm import ShmNet, _OutRing
from multiverso_tpu.runtime.tcp import TcpNet
from multiverso_tpu.util.configure import get_flag, set_flag
from multiverso_tpu.util.dashboard import Dashboard
from multiverso_tpu.util.net_util import free_listen_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not shm.supported(), reason="POSIX shared memory unavailable")

TOKEN = 0x5EED


def cnt(name):
    return Dashboard.get(name).count


def shm_entries():
    try:
        return sorted(f for f in os.listdir("/dev/shm")
                      if f.startswith("mvshm-"))
    except FileNotFoundError:  # pragma: no cover - non-tmpfs /dev/shm
        return []


class _Pair:
    """Two loopback TcpNet endpoints wrapped in ShmNet, shm-negotiated
    both ways — the whole transport stack minus the actor layer."""

    def __init__(self, ring_slots=None, slot_kb=None):
        self._saved = {}
        for flag, value in (("shm_ring_slots", ring_slots),
                            ("shm_slot_kb", slot_kb)):
            if value is not None:
                self._saved[flag] = get_flag(flag)
                set_flag(flag, value)
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        self.nets = [ShmNet(TcpNet(r, eps)) for r in range(2)]
        for net in self.nets:
            net.enable_shm(TOKEN, [1 - net.rank])

    def close(self):
        for net in self.nets:
            net.finalize()
        for flag, value in self._saved.items():
            set_flag(flag, value)


@pytest.fixture
def pair(request):
    kwargs = getattr(request, "param", {})
    p = _Pair(**kwargs)
    yield p
    p.close()


def data_msg(src, dst, msg_id, payload):
    msg = Message(src=src, dst=dst, msg_type=MsgType.Request_Get,
                  msg_id=msg_id)
    msg.push(Blob(payload))
    return msg


def test_ring_roundtrip_byte_identical_and_in_place(pair):
    """A single-slot frame crosses the ring byte-identical, lands as a
    read-only view INTO the shared segment (no receive copy), and the
    ring frame counters move while the chunk-copy counter does not."""
    n0, n1 = pair.nets
    payload = np.arange(1024, dtype=np.float32)
    frames_before = cnt("SHM_FRAMES")
    copied_before = cnt("SHM_BYTES_COPIED")
    n0.send(data_msg(0, 1, 7, payload))
    msg = n1.recv(timeout=30)
    assert msg is not None and msg.msg_id == 7
    arr = msg.data[0].as_array(np.float32)
    np.testing.assert_array_equal(arr, payload)
    # In-place contract: pool-backed (a lease rides the blob) and
    # read-only (writing through a shared slot would corrupt the ring).
    assert msg.data[0].pool_backed
    assert not arr.flags.writeable
    assert cnt("SHM_FRAMES") > frames_before
    assert cnt("SHM_BYTES_COPIED") == copied_before
    assert n0.is_shm_peer(1) and n1.is_shm_peer(0)


def test_sync_and_async_sends_stay_fifo(pair):
    """Interleaved sync/async sends arrive FIFO. A reader thread
    drains concurrently: undelivered in-place frames hold their slots,
    so 200 frames through a 16-slot ring NEED a live consumer — the
    production shape (the communicator's recv thread always drains)."""
    n0, n1 = pair.nets
    total = 200
    got, errors = [], []

    def reader():
        try:
            for _ in range(total):
                msg = n1.recv(timeout=30)
                assert msg is not None
                got.append((msg.msg_id,
                            float(msg.data[0].as_array(np.float32)[0])))
                msg = None  # release the slot lease before the ring wraps
        except BaseException as exc:  # pragma: no cover - surfaced below
            errors.append(exc)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for i in range(total):
        msg = data_msg(0, 1, i, np.full(64, float(i), np.float32))
        if i % 3 == 0:
            n0.send(msg)
        else:
            n0.send_async(msg)
    n0.flush_sends(timeout=30)
    t.join(timeout=60)
    assert not t.is_alive() and not errors, errors
    assert got == [(i, float(i)) for i in range(total)]


@pytest.mark.parametrize("pair", [{"ring_slots": 2}], indirect=True)
def test_ring_saturation_blocks_writer_not_caller(pair):
    """Satellite 1: a slow reader saturates the 2-slot ring; the writer
    thread blocks with bounded backpressure (counted once per episode),
    every frame still arrives, in order."""
    n0, n1 = pair.nets
    waits_before = cnt("SHM_RING_FULL_WAITS")
    total = 40
    for i in range(total):
        n0.send_async(data_msg(0, 1, i, np.full(256, float(i),
                                                np.float32)))
    # The inbox holds slot leases, so with nobody receiving the ring
    # must fill and the writer must park in _wait_free.
    deadline = time.monotonic() + 20
    while cnt("SHM_RING_FULL_WAITS") == waits_before:
        assert time.monotonic() < deadline, "writer never saturated"
        time.sleep(0.01)
    for i in range(total):
        msg = n1.recv(timeout=30)
        assert msg is not None and msg.msg_id == i, (i, msg)
        msg = None
    n0.flush_sends(timeout=30)
    assert cnt("SHM_RING_FULL_WAITS") > waits_before


@pytest.mark.parametrize("pair", [{"ring_slots": 4}], indirect=True)
def test_pinned_slots_degrade_to_copies_not_deadlock(pair):
    """A consumer sitting on delivered frames (the allreduce engine's
    out-of-order stash is the real-world shape) pins at most half the
    ring: past that, frames copy out through the pool (SHM_PIN_COPIES)
    and the writer keeps flowing — 3x the ring's worth of frames all
    held live, nothing released, no deadlock."""
    n0, n1 = pair.nets
    pins_before = cnt("SHM_PIN_COPIES")
    held = []
    for i in range(12):
        n0.send(data_msg(0, 1, i, np.full(64, float(i), np.float32)))
        msg = n1.recv(timeout=30)
        assert msg is not None and msg.msg_id == i
        held.append(msg)
    assert cnt("SHM_PIN_COPIES") > pins_before
    for i, msg in enumerate(held):
        np.testing.assert_array_equal(msg.data[0].as_array(np.float32),
                                      np.full(64, float(i), np.float32))


@pytest.mark.parametrize("pair", [{"ring_slots": 4}], indirect=True)
def test_parked_slot_recycles_after_view_dies(pair):
    """A numpy view held past its Message parks the slot (the lease's
    weakref probe sees the backing array still alive); once the view
    dies the poller's re-probe frees it and the ring keeps flowing."""
    n0, n1 = pair.nets
    parked_before = cnt("SHM_SLOT_PARKED")
    n0.send(data_msg(0, 1, 0, np.arange(32, dtype=np.float32)))
    msg = n1.recv(timeout=30)
    held = msg.data[0].as_array(np.float32)  # pins the backing array
    msg = None  # lease release sees a live weakref -> park
    deadline = time.monotonic() + 20
    while cnt("SHM_SLOT_PARKED") == parked_before:
        assert time.monotonic() < deadline, "slot never parked"
        time.sleep(0.01)
    np.testing.assert_array_equal(held,
                                  np.arange(32, dtype=np.float32))
    held = None  # now the re-probe can free the slot
    # More frames than remaining slots: delivery proves the parked
    # slot really recycled (the writer would otherwise block forever
    # at wraparound).
    for i in range(1, 9):
        n0.send(data_msg(0, 1, i, np.full(32, float(i), np.float32)))
        msg = n1.recv(timeout=30)
        assert msg is not None and msg.msg_id == i
        msg = None


@pytest.mark.parametrize("pair", [{"ring_slots": 2, "slot_kb": 1}],
                         indirect=True)
def test_oversize_frame_chunks_through_the_pool(pair):
    """A frame bigger than the whole ring streams as chunk slots and
    reassembles through the receive pool — the one counted copy below
    the socket."""
    n0, n1 = pair.nets
    chunked_before = cnt("SHM_CHUNKED_FRAMES")
    copied_before = cnt("SHM_BYTES_COPIED")
    payload = np.random.default_rng(3).random(16384).astype(np.float32)
    # Async submit: the frame is bigger than the whole ring, so the
    # WRITER thread must stall mid-frame until this thread's recv
    # processes the announce and the poller starts freeing chunk slots.
    n0.send_async(data_msg(0, 1, 11, payload))
    msg = n1.recv(timeout=30)
    assert msg is not None and msg.msg_id == 11
    np.testing.assert_array_equal(msg.data[0].as_array(np.float32),
                                  payload)
    n0.flush_sends(timeout=30)
    assert cnt("SHM_CHUNKED_FRAMES") > chunked_before
    assert cnt("SHM_BYTES_COPIED") >= copied_before + payload.nbytes


def test_chaos_frames_apply_to_ring_sends(pair):
    """Satellite 3: -chaos_frames reaches shm sends — a drop=1 spec
    swallows ring-routed data frames exactly as it would TCP ones."""
    n0, n1 = pair.nets
    # Prime the ring so the announce/attach cycle is done before chaos
    # arms (the announce is ctrl-band and must not be dropped here).
    n0.send(data_msg(0, 1, 0, np.zeros(16, np.float32)))
    assert n1.recv(timeout=30) is not None
    dropped_before = cnt("CHAOS_DROPPED")
    set_flag("chaos_frames", "drop=1,classes=data,seed=3")
    try:
        n0.send_async(data_msg(0, 1, 1, np.ones(16, np.float32)))
        n0.flush_sends(timeout=30)
        assert cnt("CHAOS_DROPPED") > dropped_before
        assert n1.recv(timeout=0.4) is None
    finally:
        set_flag("chaos_frames", "")


def test_finalize_unlinks_segments(pair):
    n0, n1 = pair.nets
    for src, dst in ((0, 1), (1, 0)):
        pair.nets[src].send(data_msg(src, dst, 5,
                                     np.zeros(64, np.float32)))
        msg = pair.nets[dst].recv(timeout=30)
        assert msg is not None
        msg = None
    names = {shm._seg_name(TOKEN, 0, 1), shm._seg_name(TOKEN, 1, 0)}
    assert names <= set(shm_entries()), shm_entries()
    pair.close()
    assert not names & set(shm_entries()), shm_entries()


def test_blob_outlives_segment_via_graveyard(pair):
    """Satellite 2 memory-safety half: a zero-copy view kept past
    transport teardown stays valid (the mapping parks on the module
    graveyard instead of unmapping) while the NAME is still unlinked."""
    n0, n1 = pair.nets
    payload = np.arange(128, dtype=np.float32)
    n0.send(data_msg(0, 1, 9, payload))
    msg = n1.recv(timeout=30)
    blob = msg.data[0]
    msg = None
    pair.close()
    assert shm._seg_name(TOKEN, 0, 1) not in shm_entries()
    np.testing.assert_array_equal(blob.as_array(np.float32), payload)


def test_rejoin_create_reaps_stale_segment():
    """Satellite 2: a SIGKILL'd rank's replacement reclaims its own
    stale segment name at create (FileExistsError path) instead of
    failing or leaking."""
    stale = _OutRing.create(TOKEN, 97, 98)  # "dies" without destroy
    name = stale.name
    assert name in shm_entries()
    fresh = _OutRing.create(TOKEN, 97, 98)
    assert fresh.name == name and fresh.nonce != stale.nonce
    fresh.destroy()
    assert name not in shm_entries()
    stale.destroy()  # unmap the simulated-dead mapping; unlink is a no-op


def test_atexit_reap_covers_crashed_process():
    """A process that dies by unhandled exception never reaches
    finalize; the atexit hook unlinks whatever it created."""
    ring = _OutRing.create(TOKEN, 95, 96)
    assert ring.name in shm_entries()
    shm._atexit_reap()
    assert ring.name not in shm_entries()
    ring.destroy()  # unmap; the unlink half is a handled no-op


# ---------------------------------------------------------------------------
# Subprocess clusters: interop + lifecycle hygiene
# ---------------------------------------------------------------------------

PRELUDE = """
import os, sys
import faulthandler
faulthandler.dump_traceback_later(200, exit=True)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import multiverso_tpu as mv
rank = int(os.environ["MV_RANK"])
"""


def run_cluster(bodies, timeout=240, expect_rc=None):
    """run_cluster twin (test_net_integration) that also returns
    stderr: every shm cluster test asserts no resource_tracker noise."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=REPO)
    procs = [subprocess.Popen(
        [sys.executable, "-c", PRELUDE.format(repo=REPO) + body],
        env=dict(env, MV_RANK=str(rank)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for rank, body in enumerate(bodies)]
    outs, errs, failures = [], [], []
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            out, err = p.communicate()
            failures.append(f"rank {rank} TIMED OUT:\n{err[-1500:]}")
            continue
        outs.append(out)
        errs.append(err)
        want = 0 if expect_rc is None else expect_rc.get(rank, 0)
        if p.returncode != want and want is not None:
            failures.append(f"rank {rank} rc={p.returncode}:"
                            f"\n{err[-1500:]}")
    assert not failures, "\n---\n".join(failures)
    for rank, err in enumerate(errs):
        assert "resource_tracker" not in err, (
            f"rank {rank} leaked resource_tracker noise:\n{err[-1500:]}")
    return outs


def write_machine_file(tmp_path, n):
    ports = [free_listen_port() for _ in range(n)]
    mf = tmp_path / "machines"
    mf.write_text("".join(f"127.0.0.1:{p}\n" for p in ports))
    return str(mf)


_TABLE_BODY = """
mv.init(["-machine_file={mf}", "-rank=" + str(rank){extra}])
table = mv.create_array_table(16)
table.add((np.arange(16, dtype=np.float32) + 1.0) * (rank + 1))
mv.barrier()
out = table.get()
mv.barrier()
import hashlib
print("DIGEST", hashlib.sha256(out.astype("<f4").tobytes()).hexdigest())
from multiverso_tpu.util.dashboard import Dashboard
print("SHM_FRAMES", Dashboard.get("SHM_FRAMES").count)
mv.shutdown()
print("TABLE_OK")
"""


def _digests(outs):
    return [line.split()[1] for o in outs for line in o.splitlines()
            if line.startswith("DIGEST")]


def test_mixed_transport_cluster_byte_identical(tmp_path):
    """Satellite 3: 2 shm ranks + 1 -shm=0 TCP rank produce results
    byte-identical to an all-TCP cluster, and the shm pair really does
    ride the rings."""
    n = 3
    mixed = [_TABLE_BODY.format(mf=write_machine_file(tmp_path, n),
                                extra=', "-shm=0"' if r == 2 else "")
             for r in range(n)]
    outs_mixed = run_cluster(mixed)
    all_tcp = [_TABLE_BODY.format(mf=write_machine_file(tmp_path, n),
                                  extra=', "-shm=0"')
               for _ in range(n)]
    outs_tcp = run_cluster(all_tcp)
    assert all("TABLE_OK" in o for o in outs_mixed + outs_tcp)
    dig_mixed, dig_tcp = _digests(outs_mixed), _digests(outs_tcp)
    assert len(set(dig_mixed)) == 1 and len(set(dig_tcp)) == 1
    assert dig_mixed[0] == dig_tcp[0], (dig_mixed, dig_tcp)
    frames = {r: int(line.split()[1])
              for r, o in enumerate(outs_mixed) for line in o.splitlines()
              if line.startswith("SHM_FRAMES")}
    # The co-located shm pair used its rings; the -shm=0 rank did not.
    assert frames[0] > 0 or frames[1] > 0, frames
    assert frames[2] == 0, frames
    assert all(int(line.split()[1]) == 0 for o in outs_tcp
               for line in o.splitlines()
               if line.startswith("SHM_FRAMES"))
    assert not shm_entries(), shm_entries()


def test_sigkill_and_survivor_reap(tmp_path):
    """Satellite 2: a rank SIGKILLs itself mid-run (no goodbye, no
    atexit); the survivor aborts cleanly and reaps the dead rank's
    segment at finalize — /dev/shm ends empty."""
    mf = write_machine_file(tmp_path, 2)
    survivor = f"""
from multiverso_tpu.runtime.zoo import ClusterAborted
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
table = mv.create_array_table(4)
table.add(np.ones(4, np.float32))
mv.barrier()
try:
    mv.barrier()
except ClusterAborted:
    print("ABORTED_OK")
mv.shutdown(finalize_net=True)
"""
    dier = f"""
import signal
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
table = mv.create_array_table(4)
table.add(np.ones(4, np.float32))
mv.barrier()
os.kill(os.getpid(), signal.SIGKILL)
"""
    outs = run_cluster([survivor, dier],
                       expect_rc={0: 0, 1: -9})
    assert "ABORTED_OK" in outs[0], outs[0]
    assert not shm_entries(), shm_entries()
