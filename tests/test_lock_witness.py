"""Runtime lock-order witness tests (util/lock_witness.py).

The deliberate AB/BA test pins the cycle detector's contract: both
threads, both locks, and both acquisition stacks are named, and the
report fires at ACQUIRE time on the second ordering — before any
actual deadlock can form. The LocalCluster smoke run pins the other
half of the contract: the real two-rank pipeline is witness-clean.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from multiverso_tpu.util import lock_witness as lw
from multiverso_tpu.util.configure import set_flag


@pytest.fixture(autouse=True)
def _witness_on():
    set_flag("debug_locks", True)
    lw.reset()
    yield
    lw.reset()
    # Witness-era wrappers persist on anything registered process-wide
    # (Dashboard monitors); drop them so later test modules run on
    # plain primitives. conftest's _reset_flags restores
    # debug_locks=False afterwards.
    from multiverso_tpu.util.dashboard import Dashboard
    Dashboard.reset()


class TestWitnessCore:
    def test_ab_ba_cycle_fires_with_both_stacks(self):
        lock_a = lw.named_lock("witness.A")
        lock_b = lw.named_lock("witness.B")
        ab_done = threading.Event()
        caught = []

        def first():  # establishes A -> B
            with lock_a:
                with lock_b:
                    pass
            ab_done.set()

        def second():  # attempts B -> A: must report, not deadlock
            ab_done.wait(timeout=5)
            try:
                with lock_b:
                    with lock_a:
                        pass
            except lw.LockOrderError as exc:
                caught.append(str(exc))

        t1 = threading.Thread(target=first, name="wit-first")
        t2 = threading.Thread(target=second, name="wit-second")
        t1.start()
        t2.start()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert caught, "AB/BA ordering did not raise LockOrderError"
        report = caught[0]
        # Both locks, both threads, both stacks.
        assert "witness.A" in report and "witness.B" in report
        assert "wit-first" in report and "wit-second" in report
        assert report.count("test_lock_witness.py") >= 2
        # Also queryable after the fact.
        assert len(lw.reports()) == 1

    def test_consistent_order_stays_silent(self):
        lock_a = lw.named_lock("witness.C")
        lock_b = lw.named_lock("witness.D")

        def worker():
            for _ in range(50):
                with lock_a:
                    with lock_b:
                        pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert lw.reports() == []

    def test_three_lock_cycle_detected(self):
        locks = {n: lw.named_lock(f"witness.3{n}") for n in "XYZ"}
        with locks["X"]:
            with locks["Y"]:
                pass
        with locks["Y"]:
            with locks["Z"]:
                pass
        with pytest.raises(lw.LockOrderError, match="cycle"):
            with locks["Z"]:
                with locks["X"]:
                    pass

    def test_rlock_reentry_is_not_an_edge(self):
        rlock = lw.named_rlock("witness.R")
        other = lw.named_lock("witness.R2")
        with rlock:
            with rlock:  # re-entrant: no self-edge, no crash
                with other:
                    pass
        assert lw.reports() == []

    def test_rlock_reentry_through_another_lock_is_silent(self):
        # R -> A -> R (re-entrant) must NOT read as an A -> R ordering
        # edge closing a cycle with R -> A: the inner acquire is a
        # re-entry of a lock this thread already holds — exactly the
        # TABLE_LOCK shape (sync-server drain paths re-enter through
        # Server._process_* while table helpers take per-cache locks).
        rlock = lw.named_rlock("witness.R3")
        other = lw.named_lock("witness.R4")
        with rlock:
            with other:
                with rlock:
                    pass
        assert lw.reports() == []

    def test_condition_wait_releases_held_set(self):
        cond = lw.named_condition("witness.cond")
        lock = lw.named_lock("witness.cond_peer")
        woke = []

        def waiter():
            with cond:
                woke.append(cond.wait(timeout=5))

        t = threading.Thread(target=waiter)
        t.start()
        # Take cond from this thread while the waiter is blocked in
        # wait(): only possible because wait released the lock; the
        # held-set must agree or this acquire would record a bogus
        # edge from the waiter's frame.
        import time
        time.sleep(0.1)
        with lock:
            with cond:
                cond.notify_all()
        t.join(timeout=5)
        assert woke == [True]
        assert lw.reports() == []

    # Bare acquire probes below are the point of the test.
    def test_plain_lock_self_reentry_reports_not_hangs(self):  # mvlint: ignore[lock-discipline]
        # Re-acquiring a held NON-reentrant lock with an unbounded
        # blocking acquire is the simplest deadlock there is: the
        # witness must report it instead of silently hanging.
        lock = lw.named_lock("witness.self")
        with lock:
            with pytest.raises(lw.LockOrderError,
                               match="self-deadlock"):
                lock.acquire()
            # Bounded probes keep their normal failure semantics
            # (acquire_timeout on a wedged lock must return False,
            # not raise).
            assert lock.acquire(timeout=0.05) is False
            assert lock.acquire(blocking=False) is False
        assert len(lw.reports()) == 1

    def test_bounded_probe_never_reports_a_cycle(self):
        # The sanctioned shutdown idiom: after an A->B ordering is on
        # record, a BOUNDED acquire of A while holding B (tcp.py
        # finalize's acquire_timeout shape) must fail or succeed
        # normally — never raise — and must not record a B->A edge.
        lock_a = lw.named_lock("witness.bnd_A")
        lock_b = lw.named_lock("witness.bnd_B")
        with lock_a:
            with lock_b:
                pass
        with lock_b:
            with lw.acquire_timeout(lock_a, 0.2) as got:
                assert got  # uncontended: bounded acquire succeeds
        assert lw.reports() == []
        # And the full-cycle path is still armed for unbounded
        # acquires after the probes above.
        with pytest.raises(lw.LockOrderError):
            with lock_b:
                with lock_a:
                    pass

    def test_wait_without_acquire_does_not_poison_held_set(self):
        cond = lw.named_condition("witness.unheld")
        with pytest.raises(RuntimeError):
            cond.wait(timeout=0.1)  # not acquired: stdlib raises
        # The failed wait must not leave a phantom held entry that
        # turns the next legitimate acquire into a self-deadlock.
        with cond:
            pass
        assert lw.reports() == []

    def test_acquire_timeout_helper(self):
        lock = lw.named_lock("witness.timeout")
        with lw.acquire_timeout(lock, 1.0) as got:
            assert got
            with lw.acquire_timeout(lock, 0.05) as nested:
                assert not nested  # held: bounded acquire must fail
        with lw.acquire_timeout(lock, 1.0) as again:
            assert again  # released on exit despite the failed nest

    def test_client_cache_locks_are_per_instance(self):
        # The order graph is keyed by NAME: two tables' caches sharing
        # one name would hide real cross-table cycles and manufacture
        # false ones.
        from multiverso_tpu.tables.client_cache import VersionTracker
        t1, t2 = VersionTracker(), VersionTracker()
        assert t1._lock.name != t2._lock.name

    def test_disabled_factories_return_plain_primitives(self):
        set_flag("debug_locks", False)
        assert isinstance(lw.named_lock("x"), type(threading.Lock()))
        cond = lw.named_condition("y")
        assert isinstance(cond, threading.Condition)


class TestClusterSmoke:
    def test_two_rank_table_traffic_stays_silent(self):
        # -debug_locks on BEFORE the cluster builds its queues/locks:
        # every MtQueue, Waiter, fabric condition and runtime lock
        # constructed for the run is witnessed. Plain PS table traffic
        # must produce zero lock-order reports.
        import multiverso_tpu as mv
        from multiverso_tpu.runtime.cluster import LocalCluster

        def body(rank):
            zoo = mv.current_zoo()
            table = mv.create_array_table(256)
            zoo.barrier()
            for step in range(5):
                table.add(np.full(256, rank + 1, np.float32))
                values = table.get()
                assert values.shape == (256,)
            zoo.barrier()
            return float(table.get()[0])

        totals = LocalCluster(2).run(body)
        assert len(totals) == 2
        assert lw.reports() == [], lw.reports()

    def test_two_rank_device_pipeline_stays_silent(self, tmp_path):
        # The PR-4 wedge workload itself: two virtual worker ranks
        # driving the device-key PS pipeline against one shared server,
        # under the witness. One epoch is enough to cross every lock
        # site (mailboxes, waiters, caches, fabric, dispatch guards).
        import multiverso_tpu as mv
        from multiverso_tpu.models.wordembedding import (
            Dictionary, PSDeviceCorpusTrainer, PSWord2Vec,
            TokenizedCorpus, Word2VecConfig)
        from multiverso_tpu.runtime.cluster import LocalCluster

        rng = np.random.default_rng(0)
        words = [f"w{i}" for i in range(16)]
        path = tmp_path / "corpus.txt"
        path.write_text("\n".join(
            " ".join(rng.choice(words, size=12)) for _ in range(120)))
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))

        def body(rank):
            config = Word2VecConfig(embedding_size=8, window=2,
                                    epochs=1, init_learning_rate=0.01,
                                    batch_size=512, sample=0)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=64)
            loss, pairs = trainer.train_epoch(seed=rank)
            assert np.isfinite(loss) and pairs > 0
            mv.current_zoo().barrier()
            return True

        assert LocalCluster(2, roles=["all", "worker"]).run(body) \
            == [True, True]
        assert lw.reports() == [], lw.reports()
