"""Runtime tests: zoo bootstrap, registration, barrier, vector clocks.

Mirrors the reference's in-process PS environment trick
(ref: Test/unittests/multiverso_env.h:9-31) and multi-rank integration
tests run under mpirun (ref: deploy/docker/Dockerfile:100-110), here on an
in-process virtual cluster.
"""

import threading
import time

import pytest

import multiverso_tpu as mv
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.runtime.server import _VectorClock


def test_init_shutdown_single_rank():
    mv.init([])
    assert mv.rank() == 0
    assert mv.size() == 1
    assert mv.num_workers() == 1
    assert mv.num_servers() == 1
    assert mv.worker_id() == 0
    assert mv.server_id() == 0
    mv.barrier()
    mv.shutdown()


def test_init_parses_flags_and_returns_rest():
    rest = mv.init(["prog", "-sync=true", "-other_thing=1"])
    assert rest == ["prog", "-other_thing=1"]
    from multiverso_tpu.util.configure import get_flag
    assert get_flag("sync") is True
    mv.shutdown()


def test_multirank_registration_assigns_dense_ids():
    def body(rank):
        zoo = mv.current_zoo()
        assert zoo.size == 4
        assert zoo.num_workers == 4
        assert zoo.num_servers == 4
        assert zoo.worker_id == zoo.rank  # dense, rank order
        assert zoo.server_rank(zoo.server_id) == zoo.rank
        zoo.barrier()
        return zoo.rank

    assert LocalCluster(4).run(body) == [0, 1, 2, 3]


def test_worker_only_and_server_only_roles():
    # Heterogeneous roles: rank0=all, rank1=worker-only, rank2=server-only.
    # Dense id assignment in rank order (ref: src/controller.cpp:46-66).
    def body(rank):
        zoo = mv.current_zoo()
        assert zoo.num_workers == 2
        assert zoo.num_servers == 2
        assert zoo.worker_rank(0) == 0 and zoo.worker_rank(1) == 1
        assert zoo.server_rank(0) == 0 and zoo.server_rank(1) == 2
        return (zoo.worker_id, zoo.server_id)

    result = LocalCluster(3, roles=["all", "worker", "server"]).run(body)
    assert result == [(0, 0), (1, -1), (-1, 1)]


def test_barrier_actually_blocks():
    arrived = []

    def body(rank):
        if rank == 1:
            time.sleep(0.2)
        arrived.append(rank)
        zoo = mv.current_zoo()
        zoo.barrier()
        # After barrier, every rank must have arrived.
        assert sorted(arrived) == [0, 1]
        return True

    assert LocalCluster(2).run(body) == [True, True]


class TestVectorClock:
    def test_update_levels_when_all_tick(self):
        clock = _VectorClock(3)
        assert not clock.update(0)
        assert not clock.update(1)
        assert clock.update(2)  # all at 1 -> global catches max
        assert clock.global_clock == 1

    def test_faster_worker_does_not_level(self):
        clock = _VectorClock(2)
        assert not clock.update(0)
        assert not clock.update(0)  # worker 0 at 2, worker 1 at 0
        assert not clock.update(1)  # min=1 -> global 1, max=2 -> not level
        assert clock.global_clock == 1
        assert clock.update(1)  # both at 2
        assert clock.global_clock == 2

    def test_finish_train_releases(self):
        clock = _VectorClock(2)
        clock.update(0)
        assert clock.finish_train(1)  # worker 1 retires; global -> max(1)
        assert clock.global_clock == 1


def test_error_on_one_rank_surfaces_quickly():
    # A failing rank must abort the cluster (unblocking sibling barriers),
    # not hang until the join timeout.
    def body(rank):
        if rank == 1:
            raise ZeroDivisionError("boom")
        mv.current_zoo().barrier()  # would mispair without abort
        return rank

    cluster = LocalCluster(2)
    cluster.timeout = 15
    start = time.monotonic()
    with pytest.raises(ZeroDivisionError):
        cluster.run(body)
    assert time.monotonic() - start < 10


def test_ma_mode_skips_ps():
    mv.init(["-ma=true"])
    zoo = mv.current_zoo()
    assert zoo.num_workers == 0  # no PS actors
    with pytest.raises(RuntimeError):
        zoo.send_to("worker", None)
    mv.shutdown()


class TestAddCoalescing:
    """Deterministic coverage of the worker's shard-message coalescing
    (the TCP two-process flavor in test_net_integration.py exercises it
    end to end but cannot control mailbox timing)."""

    class _FakeNet:
        in_process = False

    class _FakeZoo:
        def __init__(self):
            self.rank = 1
            self.num_servers = 2
            self.net = TestAddCoalescing._FakeNet()
            self.sent = []
            self._actors = {}

        def register_actor(self, actor):
            self._actors[actor.name] = actor

        def deregister_actor(self, actor):
            self._actors.pop(actor.name, None)

        def send_to(self, name, msg):
            self.sent.append((name, msg))

        def server_rank(self, server_id):
            return server_id  # server 0 remote (rank 0), server 1 local

        def rank_to_server_id(self, rank):
            return rank  # dense map, mirroring server_rank above

    class _FakeTable:
        def __init__(self):
            self.events = []

        def partition(self, blobs, msg_type):
            return {0: list(blobs), 1: list(blobs)}

        def reset(self, msg_id, n):
            self.events.append(("reset", msg_id, n))

        def notify(self, msg_id):
            self.events.append(("notify", msg_id))

        def fail(self, msg_id, reason, count=True):
            self.events.append(("fail", msg_id, reason))

        def note_version(self, server_id, version):
            self.events.append(("version", server_id, version))

        def note_add_ack(self, server_id, version):
            # Add acks carry the version AND raise the RYW floor
            # (table_interface.note_add_ack); the fake only records.
            self.events.append(("version", server_id, version))

        def abort(self, reason):
            self.events.append(("abort", reason))

    def _worker(self):
        import numpy as np

        from multiverso_tpu.core.blob import Blob
        from multiverso_tpu.core.message import Message, MsgType
        from multiverso_tpu.runtime.worker import Worker
        from multiverso_tpu.util.configure import set_flag
        set_flag("sync", False)
        set_flag("coalesce_adds", True)
        zoo = self._FakeZoo()
        worker = Worker(zoo)  # thread never started: drive handlers
        table = self._FakeTable()
        worker.register_table(table)
        def add(msg_id):
            msg = Message(src=1, dst=-1, msg_type=MsgType.Request_Add,
                          table_id=0, msg_id=msg_id)
            msg.push(Blob(np.ones(4, np.float32)))
            return msg
        return worker, zoo, table, add, Message, MsgType

    def test_remote_shards_stage_local_shards_send(self):
        worker, zoo, table, add, Message, MsgType = self._worker()
        assert worker._coalesce
        worker._process_add(add(1))
        worker._process_add(add(2))
        # Local (dst == own rank) shards went straight out; remote
        # shards are staged for dst rank 0.
        assert [m.dst for _, m in zoo.sent] == [1, 1]
        assert all(m.type == MsgType.Request_Add for _, m in zoo.sent)
        assert len(worker._pending[0]) == 2
        # A Get flushes the staged adds FIRST (add-before-get order on
        # the wire), as one Request_BatchAdd.
        get = Message(src=1, dst=-1, msg_type=MsgType.Request_Get,
                      table_id=0, msg_id=3)
        worker._process_get(get)
        types = [m.type for _, m in zoo.sent]
        batch_at = types.index(MsgType.Request_BatchAdd)
        first_get_at = types.index(MsgType.Request_Get)
        assert batch_at < first_get_at
        assert not worker._pending
        from multiverso_tpu.core.message import unpack_add_batch
        batch = next(m for _, m in zoo.sent
                     if m.type == MsgType.Request_BatchAdd)
        subs = unpack_add_batch(batch)
        assert [s.msg_id for s in subs] == [1, 2]
        assert batch.dst == 0

    def test_count_cap_flushes(self):
        worker, zoo, table, add, Message, MsgType = self._worker()
        for i in range(worker._max_batch_msgs):
            worker._process_add(add(i))
        batches = [m for _, m in zoo.sent
                   if m.type == MsgType.Request_BatchAdd]
        assert len(batches) == 1  # cap reached -> flushed mid-burst
        assert not worker._pending

    def test_single_staged_shard_sends_plain(self):
        worker, zoo, table, add, Message, MsgType = self._worker()
        worker._process_add(add(7))
        worker._flush_pending()
        remote = [m for _, m in zoo.sent if m.dst == 0]
        assert len(remote) == 1 and remote[0].type == MsgType.Request_Add

    def test_sync_mode_disables_coalescing(self):
        import numpy as np

        from multiverso_tpu.runtime.worker import Worker
        from multiverso_tpu.util.configure import set_flag
        set_flag("sync", True)
        try:
            zoo = self._FakeZoo()
            worker = Worker(zoo)
            assert not worker._coalesce
        finally:
            set_flag("sync", False)

    def test_malformed_batch_still_acks_every_sub(self):
        # The reply must go out in EVERY path: a truncated batch (blob
        # count disagrees with the descriptor) acks each sub the
        # descriptor names as FAILED, so no waiter strands (same
        # invariant as the per-message handlers' finally-send).
        import numpy as np

        from multiverso_tpu.core.blob import Blob
        from multiverso_tpu.core.message import (Message, MsgType,
                                                 pack_add_batch)
        from multiverso_tpu.runtime.server import Server
        zoo = self._FakeZoo()
        server = Server(zoo)
        subs = []
        for i in range(2):
            sub = Message(src=1, dst=0, msg_type=MsgType.Request_Add,
                          table_id=0, msg_id=50 + i)
            sub.push(Blob(np.ones(4, np.float32)))
            subs.append(sub)
        batch = pack_add_batch(subs)
        batch.data = batch.data[:-1]  # truncate a payload blob
        server._process_batch_add(batch)
        replies = [m for _, m in zoo.sent
                   if m.type == MsgType.Reply_BatchAdd]
        assert len(replies) == 1
        desc = replies[0].data[0].as_array(np.int32)
        assert desc[0] == 2
        # Stride-4 descriptor: (table_id, msg_id, err, version); the
        # unpack-failure path cannot resolve versions (-1).
        assert list(desc[1:9]) == [0, 50, 1, -1, 0, 51, 1, -1]

    def test_batched_reply_notifies_and_fails_per_sub(self):
        import numpy as np

        from multiverso_tpu.core.blob import Blob
        from multiverso_tpu.core.message import Message, MsgType
        worker, zoo, table, add, _, _ = self._worker()
        reply = Message(src=0, dst=1, msg_type=MsgType.Reply_BatchAdd)
        reply.push(Blob(np.array([2, 0, 11, 0, 7, 0, 12, 1, 7],
                                 np.int32)))
        reply.push(Blob(np.frombuffer(b"ValueError: boom", np.uint8)
                        .copy()))
        worker._process_reply_batch_add(reply)
        assert ("notify", 11) in table.events
        assert ("notify", 12) in table.events
        # The per-sub version stamp reaches the table's tracker (the
        # client cache's read-your-writes resolution depends on it).
        assert ("version", 0, 7) in table.events
        fails = [e for e in table.events if e[0] == "fail"]
        assert len(fails) == 1 and fails[0][1] == 12
        assert "boom" in fails[0][2]

    def test_byte_cap_flushes_exactly_at_limit(self):
        # Staged bytes crossing the -coalesce_max_kb cap must flush
        # mid-burst, exactly when the cap is reached — not one message
        # later.
        import numpy as np

        from multiverso_tpu.core.blob import Blob
        from multiverso_tpu.core.message import Message, MsgType
        worker, zoo, table, add, _, _ = self._worker()
        chunk = worker._max_batch_bytes // 4  # 4 shards hit the cap
        def big_add(msg_id):
            msg = Message(src=1, dst=-1, msg_type=MsgType.Request_Add,
                          table_id=0, msg_id=msg_id)
            msg.push(Blob(np.ones(chunk // 4, np.float32)))
            return msg
        for i in range(3):
            worker._process_add(big_add(i))
        assert not [m for _, m in zoo.sent
                    if m.type == MsgType.Request_BatchAdd]
        assert worker._pending_bytes[0] == 3 * chunk  # under the cap
        worker._process_add(big_add(3))  # reaches the cap exactly
        batches = [m for _, m in zoo.sent
                   if m.type == MsgType.Request_BatchAdd]
        assert len(batches) == 1
        assert not worker._pending and not worker._pending_bytes
        from multiverso_tpu.core.message import unpack_add_batch
        assert [s.msg_id for s in unpack_add_batch(batches[0])] \
            == [0, 1, 2, 3]

    def test_count_cap_flushes_exactly_at_limit(self):
        # The 64th staged shard (not the 65th) must trigger the flush.
        from multiverso_tpu.core.message import unpack_add_batch
        worker, zoo, table, add, Message, MsgType = self._worker()
        cap = worker._max_batch_msgs
        for i in range(cap - 1):
            worker._process_add(add(i))
        assert not [m for _, m in zoo.sent
                    if m.type == MsgType.Request_BatchAdd]
        assert len(worker._pending[0]) == cap - 1
        worker._process_add(add(cap - 1))
        batches = [m for _, m in zoo.sent
                   if m.type == MsgType.Request_BatchAdd]
        assert len(batches) == 1
        assert len(unpack_add_batch(batches[0])) == cap
        assert not worker._pending

    def test_staged_batch_survives_abort_and_drain_exit(self):
        # A staged batch interleaved with an abort must still hit the
        # wire on drain-exit: no stranded waiters (every sub keeps its
        # reset bookkeeping), no lost adds (the flush happens even
        # though the tables were just aborted).
        from multiverso_tpu.core.message import unpack_add_batch
        worker, zoo, table, add, Message, MsgType = self._worker()
        worker._process_add(add(1))
        worker._process_add(add(2))
        assert len(worker._pending[0]) == 2  # staged, not on the wire
        worker.abort_tables("peer died mid-burst")
        assert ("abort", "peer died mid-burst") in table.events
        # Drain-exit: mailbox closes, _main's exit path must flush.
        worker.mailbox.exit()
        worker._main()
        batches = [m for _, m in zoo.sent
                   if m.type == MsgType.Request_BatchAdd]
        assert len(batches) == 1
        assert [s.msg_id for s in unpack_add_batch(batches[0])] == [1, 2]
        assert not worker._pending


class TestServerLockScoping:
    def test_two_servers_progress_concurrently_on_host_paths(self,
                                                             monkeypatch):
        # Regression (BENCH_r05 ps_two_servers at 0.809x of single):
        # the process-wide table lock exists for multi-device jitted
        # dispatch; two LocalFabric servers doing HOST-side control
        # work (KV tables) must not serialize on it. Each server's
        # process_get waits for the OTHER server to enter its own
        # process_get: if the old global lock still covered KV logic,
        # one server would hold it while waiting and the other could
        # never enter — the waits time out and the flags read False.
        import multiverso_tpu as mv
        from multiverso_tpu.runtime.cluster import LocalCluster
        from multiverso_tpu.tables.kv_table import KVServer

        entered = [threading.Event(), threading.Event()]
        overlapped = [False, False]
        orig = KVServer.process_get

        def coordinated(self, blobs):
            sid = self._zoo.server_id
            entered[sid].set()
            overlapped[sid] = entered[1 - sid].wait(timeout=15)
            return orig(self, blobs)

        monkeypatch.setattr(KVServer, "process_get", coordinated)

        def body(rank):
            table = mv.create_kv_table()
            if rank == 0:
                # keys 0 and 1 hash to servers 0 and 1: one request,
                # one concurrently-processed shard per server.
                table.get([0, 1])
            mv.current_zoo().barrier()
            return True

        assert LocalCluster(2).run(body) == [True, True]
        assert overlapped == [True, True], overlapped
