"""Runtime tests: zoo bootstrap, registration, barrier, vector clocks.

Mirrors the reference's in-process PS environment trick
(ref: Test/unittests/multiverso_env.h:9-31) and multi-rank integration
tests run under mpirun (ref: deploy/docker/Dockerfile:100-110), here on an
in-process virtual cluster.
"""

import threading
import time

import pytest

import multiverso_tpu as mv
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.runtime.server import _VectorClock


def test_init_shutdown_single_rank():
    mv.init([])
    assert mv.rank() == 0
    assert mv.size() == 1
    assert mv.num_workers() == 1
    assert mv.num_servers() == 1
    assert mv.worker_id() == 0
    assert mv.server_id() == 0
    mv.barrier()
    mv.shutdown()


def test_init_parses_flags_and_returns_rest():
    rest = mv.init(["prog", "-sync=true", "-other_thing=1"])
    assert rest == ["prog", "-other_thing=1"]
    from multiverso_tpu.util.configure import get_flag
    assert get_flag("sync") is True
    mv.shutdown()


def test_multirank_registration_assigns_dense_ids():
    def body(rank):
        zoo = mv.current_zoo()
        assert zoo.size == 4
        assert zoo.num_workers == 4
        assert zoo.num_servers == 4
        assert zoo.worker_id == zoo.rank  # dense, rank order
        assert zoo.server_rank(zoo.server_id) == zoo.rank
        zoo.barrier()
        return zoo.rank

    assert LocalCluster(4).run(body) == [0, 1, 2, 3]


def test_worker_only_and_server_only_roles():
    # Heterogeneous roles: rank0=all, rank1=worker-only, rank2=server-only.
    # Dense id assignment in rank order (ref: src/controller.cpp:46-66).
    def body(rank):
        zoo = mv.current_zoo()
        assert zoo.num_workers == 2
        assert zoo.num_servers == 2
        assert zoo.worker_rank(0) == 0 and zoo.worker_rank(1) == 1
        assert zoo.server_rank(0) == 0 and zoo.server_rank(1) == 2
        return (zoo.worker_id, zoo.server_id)

    result = LocalCluster(3, roles=["all", "worker", "server"]).run(body)
    assert result == [(0, 0), (1, -1), (-1, 1)]


def test_barrier_actually_blocks():
    arrived = []

    def body(rank):
        if rank == 1:
            time.sleep(0.2)
        arrived.append(rank)
        zoo = mv.current_zoo()
        zoo.barrier()
        # After barrier, every rank must have arrived.
        assert sorted(arrived) == [0, 1]
        return True

    assert LocalCluster(2).run(body) == [True, True]


class TestVectorClock:
    def test_update_levels_when_all_tick(self):
        clock = _VectorClock(3)
        assert not clock.update(0)
        assert not clock.update(1)
        assert clock.update(2)  # all at 1 -> global catches max
        assert clock.global_clock == 1

    def test_faster_worker_does_not_level(self):
        clock = _VectorClock(2)
        assert not clock.update(0)
        assert not clock.update(0)  # worker 0 at 2, worker 1 at 0
        assert not clock.update(1)  # min=1 -> global 1, max=2 -> not level
        assert clock.global_clock == 1
        assert clock.update(1)  # both at 2
        assert clock.global_clock == 2

    def test_finish_train_releases(self):
        clock = _VectorClock(2)
        clock.update(0)
        assert clock.finish_train(1)  # worker 1 retires; global -> max(1)
        assert clock.global_clock == 1


def test_error_on_one_rank_surfaces_quickly():
    # A failing rank must abort the cluster (unblocking sibling barriers),
    # not hang until the join timeout.
    def body(rank):
        if rank == 1:
            raise ZeroDivisionError("boom")
        mv.current_zoo().barrier()  # would mispair without abort
        return rank

    cluster = LocalCluster(2)
    cluster.timeout = 15
    start = time.monotonic()
    with pytest.raises(ZeroDivisionError):
        cluster.run(body)
    assert time.monotonic() - start < 10


def test_ma_mode_skips_ps():
    mv.init(["-ma=true"])
    zoo = mv.current_zoo()
    assert zoo.num_workers == 0  # no PS actors
    with pytest.raises(RuntimeError):
        zoo.send_to("worker", None)
    mv.shutdown()
