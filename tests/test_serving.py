"""Online serving tier (docs/SERVING.md): shared HTTP base, admission
control, mailbox-depth observability, the serving frontend's
endpoints + version/staleness metadata, and the acceptance invariant —
every served response respects the configured staleness bound while a
trainer concurrently pushes Adds."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.io.http_server import (HttpError, HttpServer,
                                           Response, json_response)
from multiverso_tpu.serving.admission import (AdmissionController,
                                              ShedError)
from multiverso_tpu.serving.frontend import ServingFrontend
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.dashboard import Dashboard, reset_samples, samples
from multiverso_tpu.util.mt_queue import MtQueue
from multiverso_tpu.util.net_util import free_listen_port


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _http_error(url, timeout=10):
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(url, timeout=timeout)
    err = exc.value
    body = json.loads(err.read())
    return err.code, dict(err.headers), body


# ---------------------------------------------------------------------------
# shared stdlib HTTP base (io/http_server.py)
# ---------------------------------------------------------------------------

class TestHttpServerBase:
    def _server(self, resolve):
        return HttpServer(0, resolve, host="127.0.0.1", name="test-http")

    def test_query_params_and_custom_headers(self):
        def resolve(path):
            if path != "/echo":
                return None
            return lambda query: json_response(
                {"q": query}, headers={"X-Test": "yes"})
        server = self._server(resolve)
        try:
            status, headers, doc = _get(
                f"http://127.0.0.1:{server.port}/echo?a=1&b=two&a=3")
            assert status == 200
            assert headers["X-Test"] == "yes"
            assert doc == {"q": {"a": "3", "b": "two"}}  # last wins
        finally:
            server.stop()

    def test_http_error_carries_status_headers_and_extra(self):
        def resolve(path):
            def handler(query):
                raise HttpError(429, "too busy",
                                headers={"Retry-After": "1"},
                                extra={"retry_after_s": 0.25})
            return handler
        server = self._server(resolve)
        try:
            code, headers, body = _http_error(
                f"http://127.0.0.1:{server.port}/x")
            assert code == 429
            assert headers["Retry-After"] == "1"
            assert body["retry_after_s"] == 0.25
            assert "too busy" in body["error"]
        finally:
            server.stop()

    def test_unknown_path_404_lists_describe(self):
        server = self._server(lambda path: None)
        try:
            code, _, body = _http_error(
                f"http://127.0.0.1:{server.port}/nope")
            assert code == 404
            assert "test-http" in body["error"]  # default describe()
        finally:
            server.stop()

    def test_handler_exception_is_500(self):
        def resolve(path):
            def handler(query):
                raise RuntimeError("broken")
            return handler
        server = self._server(resolve)
        try:
            code, _, body = _http_error(
                f"http://127.0.0.1:{server.port}/x")
            assert code == 500 and "broken" in body["error"]
        finally:
            server.stop()

    def test_non_200_response_passthrough(self):
        def resolve(path):
            return lambda query: Response(b"made", "text/plain",
                                          status=201)
        server = self._server(resolve)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/x",
                    timeout=10) as resp:
                assert resp.status == 201 and resp.read() == b"made"
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# admission control (serving/admission.py)
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_inflight_cap_sheds_with_retryable_error(self):
        adm = AdmissionController(max_inflight=1, shed_depth=0,
                                  retry_after_s=0.125)
        adm.admit("rows")
        with pytest.raises(ShedError) as exc:
            adm.admit("rows")
        assert exc.value.status == 429
        assert exc.value.retry_after_s == 0.125
        assert "in flight" in str(exc.value)
        # Caps are per endpoint: a different endpoint still admits.
        adm.admit("neighbors")
        adm.release("neighbors")
        adm.release("rows")
        adm.admit("rows")  # freed slot admits again
        adm.release("rows")
        stats = adm.stats()
        assert stats["shed"] == 1 and stats["admitted"] == 3
        assert stats["inflight"] == {}

    def test_depth_watermark_sheds(self):
        depth = [0]
        adm = AdmissionController(depth_of=lambda: depth[0],
                                  max_inflight=0, shed_depth=10)
        adm.admit("rows")
        adm.release("rows")
        depth[0] = 11
        with pytest.raises(ShedError) as exc:
            adm.admit("rows")
        assert "watermark" in str(exc.value)
        # shed_depth=0 disables the gate entirely.
        adm.configure(shed_depth=0)
        adm.admit("rows")
        adm.release("rows")

    def test_drain_rejects_new_with_503(self):
        adm = AdmissionController(max_inflight=0, shed_depth=0)
        assert adm.begin_drain(timeout_s=0.1) is True  # nothing in flight
        with pytest.raises(ShedError) as exc:
            adm.admit("rows")
        assert exc.value.status == 503
        assert "draining" in str(exc.value)

    def test_drain_waits_for_inflight(self):
        adm = AdmissionController(max_inflight=0, shed_depth=0)
        adm.admit("rows")
        t = threading.Timer(0.3, adm.release, args=("rows",))
        t.start()
        t0 = time.monotonic()
        assert adm.begin_drain(timeout_s=5.0) is True
        assert time.monotonic() - t0 >= 0.2  # actually waited
        t.join()

    def test_drain_timeout_reports_false(self):
        adm = AdmissionController(max_inflight=0, shed_depth=0)
        adm.admit("rows")
        assert adm.begin_drain(timeout_s=0.2) is False
        adm.release("rows")


# ---------------------------------------------------------------------------
# mailbox depth observability (util/mt_queue.py)
# ---------------------------------------------------------------------------

class TestMtQueueDepth:
    def test_high_watermark_tracks_and_resets(self):
        q = MtQueue()
        assert q.depth_high_watermark == 0
        for i in range(5):
            q.push(i)
        q.pop()
        q.pop()
        assert q.depth_high_watermark == 5  # monotonic past pops
        q.reset_depth_watermark()
        assert q.depth_high_watermark == 3  # re-anchored at current
        q.push(99)
        assert q.depth_high_watermark == 4

    def test_track_depth_records_samples(self):
        reset_samples()
        q = MtQueue()
        q.track_depth("MAILBOX_DEPTH[test]")
        for i in range(4):
            q.push(i)
        reservoir = samples("MAILBOX_DEPTH[test]")
        assert reservoir.count == 4
        snap = reservoir.snapshot()
        assert snap["max"] == 4.0 and snap["p50"] >= 1.0
        reset_samples()

    def test_server_and_worker_mailboxes_report_depth(self):
        """With a consumer enabled (-metrics_interval_s here; serving
        would too), the server/worker mailboxes feed the
        MAILBOX_DEPTH[*] family."""
        reset_samples()
        mv.init(["-metrics_interval_s=30"])
        try:
            table = mv.create_matrix_table(16, 4)
            table.add_rows(np.arange(4, dtype=np.int32),
                           np.ones((4, 4), np.float32))
            table.get_rows(np.arange(4, dtype=np.int32))
        finally:
            mv.shutdown()
        assert samples("MAILBOX_DEPTH[worker]").count > 0
        assert samples("MAILBOX_DEPTH[server]").count > 0
        reset_samples()

    def test_depth_sampling_off_without_a_consumer(self):
        """Training-only deployments (no serving, no metrics export)
        must not pay the per-push reservoir append: the samples gate
        stays closed at default flags (the high watermark alone is
        always tracked)."""
        reset_samples()
        mv.init([])
        try:
            table = mv.create_matrix_table(16, 4)
            table.add_rows(np.arange(4, dtype=np.int32),
                           np.ones((4, 4), np.float32))
            table.get_rows(np.arange(4, dtype=np.int32))
            worker = mv.current_zoo()._actors["worker"]
            assert worker.mailbox.depth_high_watermark > 0
        finally:
            mv.shutdown()
        assert samples("MAILBOX_DEPTH[worker]").count == 0
        assert samples("MAILBOX_DEPTH[server]").count == 0
        reset_samples()


# ---------------------------------------------------------------------------
# the versioned serving read (tables/matrix_table.py)
# ---------------------------------------------------------------------------

class TestReadRowsVersioned:
    def test_metadata_with_cache(self):
        mv.init([])
        set_flag("max_get_staleness", 6)
        try:
            table = mv.create_matrix_table(32, 4)
            ids = np.arange(8, dtype=np.int32)
            table.add_rows(ids, np.ones((8, 4), np.float32))
            values, meta = table.read_rows_versioned(ids)
            assert np.allclose(values, 1.0)
            assert meta["staleness_bound"] == 6
            assert meta["cache_hit"] is False  # first read fetched
            assert meta["served_version"] <= meta["latest_version"]
            values, meta = table.read_rows_versioned(ids)
            assert meta["cache_hit"] is True
            assert meta["max_staleness"] <= 6
            # An Add ages the shard; the next read re-fetches only
            # once past the bound — here it still serves locally, and
            # the reported staleness reflects the aging.
            table.add_rows(np.asarray([30], np.int32),
                           np.ones((1, 4), np.float32))
            _, meta = table.read_rows_versioned(ids)
            assert meta["cache_hit"] is True
            assert 1 <= meta["max_staleness"] <= 6
        finally:
            mv.shutdown()

    def test_metadata_cache_disabled(self):
        mv.init([])  # default flags: no cache
        try:
            table = mv.create_matrix_table(32, 4)
            ids = np.arange(8, dtype=np.int32)
            table.add_rows(ids, np.ones((8, 4), np.float32))
            _, meta = table.read_rows_versioned(ids)
            assert meta["staleness_bound"] == 0
            assert meta["cache_hit"] is False
            assert meta["max_staleness"] == 0  # everything wire-fresh
        finally:
            mv.shutdown()


# ---------------------------------------------------------------------------
# serving frontend endpoints
# ---------------------------------------------------------------------------

@pytest.fixture
def serving_env():
    """In-process PS + frontend on an ephemeral port, cache enabled."""
    mv.init([])
    set_flag("max_get_staleness", 8)
    table = mv.create_matrix_table(128, 8)
    frontend = ServingFrontend(mv.current_zoo(), port=0,
                               host="127.0.0.1")
    frontend.register_table(
        "emb", table, vocab={f"w{i}": i for i in range(128)})
    ids = np.arange(128, dtype=np.int32)
    table.add_rows(ids, np.arange(128 * 8, dtype=np.float32)
                   .reshape(128, 8))
    base = f"http://127.0.0.1:{frontend.port}"
    yield frontend, table, base
    frontend.stop()
    mv.shutdown()


class TestServingFrontend:
    def test_rows_values_and_metadata(self, serving_env):
        frontend, table, base = serving_env
        status, headers, doc = _get(base + "/v1/tables/emb/rows"
                                         "?ids=3,5,3")
        assert status == 200
        expected = np.arange(128 * 8, dtype=np.float32).reshape(128, 8)
        assert np.allclose(np.asarray(doc["rows"]),
                           expected[[3, 5, 3]])
        assert doc["ids"] == [3, 5, 3]
        assert doc["staleness_bound"] == 8
        assert doc["served_version"] <= doc["latest_version"]
        assert doc["max_staleness"] <= doc["staleness_bound"]
        assert headers["X-MV-Version"] == str(doc["served_version"])
        assert headers["X-MV-Staleness-Bound"] == "8"
        assert headers["X-MV-Cache"] in ("hit", "miss")

    def test_cache_hit_marker_flips_miss_to_hit(self, serving_env):
        frontend, table, base = serving_env
        url = base + "/v1/tables/emb/rows?ids=7,9"
        _, headers1, doc1 = _get(url)
        _, headers2, doc2 = _get(url)
        assert doc1["cache_hit"] is False
        assert headers1["X-MV-Cache"] == "miss"
        assert doc2["cache_hit"] is True
        assert headers2["X-MV-Cache"] == "hit"

    def test_listing_and_status(self, serving_env):
        frontend, table, base = serving_env
        _, _, doc = _get(base + "/v1/tables")
        assert doc["tables"] == ["emb"]
        _, _, status = _get(base + "/v1/status")
        assert status["tables"]["emb"]["num_row"] == 128
        assert status["tables"]["emb"]["vocab"] is True
        assert status["admission"]["draining"] is False
        assert "worker" in status["mailboxes"]
        assert "server" in status["mailboxes"]

    def test_unknown_table_404(self, serving_env):
        frontend, table, base = serving_env
        code, _, body = _http_error(base + "/v1/tables/nope/rows"
                                         "?ids=1")
        assert code == 404 and "'emb'" in body["error"]

    def test_bad_ids_400(self, serving_env):
        frontend, table, base = serving_env
        for query in ("", "?ids=", "?ids=a,b", "?ids=4096",
                      "?ids=-1"):
            code, _, _ = _http_error(
                base + "/v1/tables/emb/rows" + query)
            assert code == 400, query
        frontend._max_rows = 2
        code, _, body = _http_error(base + "/v1/tables/emb/rows"
                                         "?ids=1,2,3")
        assert code == 400 and "serving_max_rows" in body["error"]

    def test_neighbors_cosine_order(self, serving_env):
        frontend, table, base = serving_env
        # Overwrite the WHOLE table with known directions: rows 0-3 in
        # the (x, y) plane at 0, 10, 50, 80 degrees, everything else
        # on the z axis (cosine 0 against the query and below row 3's
        # 0.17). Neighbors of row 0 must rank 1 over 2 over 3.
        all_ids = np.arange(128, dtype=np.int32)
        current = table.get_rows(all_ids)
        vecs = np.zeros((128, 8), np.float32)
        vecs[:, 2] = 1.0
        for i, deg in enumerate((0.0, 10.0, 50.0, 80.0)):
            vecs[i] = 0.0
            vecs[i, 0] = np.cos(np.radians(deg))
            vecs[i, 1] = np.sin(np.radians(deg))
        table.add_rows(all_ids, vecs - current)
        _, headers, doc = _get(base + "/v1/tables/emb/neighbors"
                                    "?word=w0&k=3")
        ranked = [n["id"] for n in doc["neighbors"]]
        assert ranked[:3] != [0] * 3 and 0 not in ranked  # not self
        assert ranked.index(1) < ranked.index(2) < ranked.index(3)
        assert doc["neighbors"][0]["word"] == "w1"
        assert doc["query"] == {"id": 0, "word": "w0"}
        assert doc["staleness_bound"] == 8
        assert headers["X-MV-Version"] == str(doc["served_version"])
        # Same query by id.
        _, _, by_id = _get(base + "/v1/tables/emb/neighbors?id=0&k=3")
        assert [n["id"] for n in by_id["neighbors"]] == ranked

    def test_neighbors_unknown_word_404_and_bad_query_400(
            self, serving_env):
        frontend, table, base = serving_env
        code, _, _ = _http_error(base + "/v1/tables/emb/neighbors"
                                      "?word=nope")
        assert code == 404
        code, _, _ = _http_error(base + "/v1/tables/emb/neighbors")
        assert code == 400
        code, _, _ = _http_error(base + "/v1/tables/emb/neighbors"
                                      "?id=9999")
        assert code == 400

    def test_neighbor_index_refresh_follows_staleness(self,
                                                      serving_env):
        frontend, table, base = serving_env
        _, _, first = _get(base + "/v1/tables/emb/neighbors?id=1")
        assert first["index_refreshed"] is True  # cold index builds
        _, _, second = _get(base + "/v1/tables/emb/neighbors?id=1")
        assert second["index_refreshed"] is False  # fresh enough
        # Age the shard past the bound: the index must rebuild.
        for _ in range(9):  # bound is 8
            table.add_rows(np.asarray([120], np.int32),
                           np.ones((1, 8), np.float32))
        _, _, third = _get(base + "/v1/tables/emb/neighbors?id=1")
        assert third["index_refreshed"] is True
        assert third["served_version"] > first["served_version"]

    def test_shed_is_429_with_retry_after(self, serving_env):
        frontend, table, base = serving_env
        shed_before = Dashboard.get("SERVING_SHED").count
        frontend.admission.configure(max_inflight=1,
                                     retry_after_s=0.25)
        frontend.admission.admit("rows")  # occupy the only slot
        try:
            code, headers, body = _http_error(
                base + "/v1/tables/emb/rows?ids=1")
        finally:
            frontend.admission.release("rows")
        assert code == 429
        assert headers["Retry-After"] == "1"  # ceil to whole seconds
        assert body["retry_after_s"] == 0.25  # exact in the body
        assert body["shed"] is True
        assert Dashboard.get("SERVING_SHED").count == shed_before + 1
        # The slot freed: the same request now serves.
        status, _, _ = _get(base + "/v1/tables/emb/rows?ids=1")
        assert status == 200

    def test_status_answers_while_saturated(self, serving_env):
        frontend, table, base = serving_env
        frontend.admission.configure(max_inflight=1)
        frontend.admission.admit("rows")
        try:
            status, _, doc = _get(base + "/v1/status")
            assert status == 200
            assert doc["admission"]["inflight"] == {"rows": 1}
        finally:
            frontend.admission.release("rows")

    def test_graceful_drain_finishes_inflight(self, serving_env):
        frontend, table, base = serving_env
        orig = table.read_rows_versioned

        def slow_read(row_ids, out=None):
            time.sleep(0.5)
            return orig(row_ids, out)
        table.read_rows_versioned = slow_read
        result = {}

        def request():
            try:
                result["resp"] = _get(base + "/v1/tables/emb/rows"
                                           "?ids=1,2")
            except Exception as exc:  # noqa: BLE001
                result["error"] = exc
        t = threading.Thread(target=request)
        t.start()
        time.sleep(0.15)  # request is inside the slow read
        frontend.stop()   # must drain, not cut the connection
        t.join(timeout=10)
        assert "error" not in result, result
        assert result["resp"][0] == 200
        # The port is closed now.
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(base + "/v1/status", timeout=2)


# ---------------------------------------------------------------------------
# zoo wiring (-serving_port + mv.serve_table)
# ---------------------------------------------------------------------------

class TestZooWiring:
    def test_flag_starts_frontend_and_serve_table_registers(self):
        port = free_listen_port()
        mv.init([f"-serving_port={port}", "-max_get_staleness=4"])
        try:
            zoo = mv.current_zoo()
            assert zoo.serving is not None
            table = mv.create_matrix_table(16, 4)
            mv.serve_table("t", table)
            table.add_rows(np.arange(4, dtype=np.int32),
                           np.ones((4, 4), np.float32))
            _, _, doc = _get(f"http://127.0.0.1:{port}"
                             f"/v1/tables/t/rows?ids=0,1")
            assert np.allclose(doc["rows"], 1.0)
        finally:
            mv.shutdown()
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}"
                                   f"/v1/status", timeout=2)

    def test_serving_off_by_default_and_serve_table_noop(self):
        mv.init([])
        try:
            assert mv.current_zoo().serving is None
            table = mv.create_matrix_table(8, 2)
            mv.serve_table("t", table)  # must not raise
        finally:
            mv.shutdown()

    def test_non_matrix_table_rejected(self):
        mv.init([])
        try:
            frontend = ServingFrontend(mv.current_zoo(), port=0,
                                       host="127.0.0.1")
            try:
                array_table = mv.create_array_table(8)
                with pytest.raises(ValueError,
                                   match="read_rows_versioned"):
                    frontend.register_table("a", array_table)
            finally:
                frontend.stop()
        finally:
            mv.shutdown()


# ---------------------------------------------------------------------------
# acceptance: staleness bound respected while Adds land concurrently
# ---------------------------------------------------------------------------

def test_staleness_bound_respected_under_concurrent_adds():
    """The PR's serving acceptance invariant: a client hammering the
    rows endpoint while a trainer thread pushes Adds must see, on
    EVERY response, max_staleness <= staleness_bound — and both cache
    hits and misses must actually occur (the adds age entries, the
    re-fetches refresh them), proving the bound is doing work rather
    than the cache sitting idle."""
    bound = 4
    mv.init([])
    set_flag("max_get_staleness", bound)
    table = mv.create_matrix_table(256, 8)
    frontend = ServingFrontend(mv.current_zoo(), port=0,
                               host="127.0.0.1")
    frontend.register_table("emb", table)
    all_ids = np.arange(256, dtype=np.int32)
    table.add_rows(all_ids, np.ones((256, 8), np.float32))
    base = f"http://127.0.0.1:{frontend.port}"

    stop = threading.Event()
    trainer_adds = [0]

    def trainer():
        rng = np.random.default_rng(3)
        while not stop.is_set():
            ids = np.unique(rng.integers(0, 256, size=8)) \
                .astype(np.int32)
            table.add_rows(ids, np.full((ids.size, 8), 1e-3,
                                        np.float32))
            trainer_adds[0] += 1
            time.sleep(0.002)

    thread = threading.Thread(target=trainer, daemon=True)
    thread.start()
    rng = np.random.default_rng(4)
    hits = misses = 0
    try:
        for _ in range(150):
            ids = np.unique((rng.zipf(1.6, 6) - 1) % 256)
            _, _, doc = _get(base + "/v1/tables/emb/rows?ids="
                             + ",".join(str(i) for i in ids))
            assert doc["staleness_bound"] == bound
            assert doc["max_staleness"] <= bound, doc
            assert doc["served_version"] <= doc["latest_version"]
            if doc["cache_hit"]:
                hits += 1
            else:
                misses += 1
    finally:
        stop.set()
        thread.join(timeout=10)
        frontend.stop()
        mv.shutdown()
    assert trainer_adds[0] > 0
    # Both paths exercised: the adds aged entries (misses) and the
    # cache served within the bound between them (hits).
    assert misses > 0, (hits, misses, trainer_adds[0])
    assert hits > 0, (hits, misses, trainer_adds[0])
