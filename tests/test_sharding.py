"""Sharding + hot-shard replication tests (ISSUE 7, docs/SHARDING.md).

Three layers:

* unit tests for the replication pieces in ``runtime/replica.py``
  (hot tracking, routing, the holder store's version watermark, the
  controller's sticky promotion policy and per-ROUND decay) plus the
  small infrastructure they ride on (``Waiter.add_waits``, the
  ``Samples`` percentile reservoirs, the REPLICA_SLOT markers);
* routing property tests: the same op sequence against 1-server and
  N-server clusters must produce element-wise identical results across
  Array / Matrix / KV / sparse tables — including row ids sitting
  exactly on shard boundaries and row counts that do not divide evenly
  (the off-by-one class the worker-side partition audit covers);
* replica consistency integration: a write-through Add followed by a
  replica-routed Get never observes a version older than the client's
  read-your-writes floor, owner version bumps invalidate (repair)
  rather than serve stale, and demotion prunes holder stores.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.message import (Message, MsgType,
                                         mark_replica_reply,
                                         replica_row_count)
from multiverso_tpu.runtime import replica as rm
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.tables import row_offsets
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.dashboard import Dashboard, Samples
from multiverso_tpu.util.waiter import Waiter


@pytest.fixture
def env():
    mv.init([])
    yield
    mv.shutdown()


# ---------------------------------------------------------------------------
# unit: replica building blocks
# ---------------------------------------------------------------------------

class TestHotTracker:
    def test_report_counts_and_decay(self):
        t = rm.HotTracker(cadence=4)
        for _ in range(4):
            t.note(np.array([7, 7, 3], np.int32))
        assert t.due
        rows, counts = t.take_report(top_k=2)
        # Duplicate ids within one request overweight (documented), but
        # ordering by count must hold: 7 hotter than 3.
        assert rows.tolist()[0] == 7
        assert counts[0] >= counts[1]
        assert not t.due
        # Decay: a row that stops being read ages out of the dict.
        for _ in range(16):
            t.note(np.array([1], np.int32))
            if t.due:
                t.take_report(top_k=4)
        assert 7 not in t._counts or t._counts[7] < 1.0

    def test_window_deferred_not_counted_per_get(self):
        t = rm.HotTracker(cadence=100)
        t.note(np.arange(5, dtype=np.int32))
        assert t._counts == {}  # fold deferred to take_report


class TestReplicaRouter:
    def test_mask_and_stale_epoch(self):
        r = rm.ReplicaRouter(4, salt=0)
        assert not r.active
        assert r.apply(3, np.array([5, 9], np.int32))
        assert r.active and r.epoch == 3
        # Reordered (stale) broadcast must be ignored.
        assert not r.apply(2, np.array([1], np.int32))
        mask = r.replicated_mask(np.array([1, 5, 8, 9], np.int32))
        assert mask.tolist() == [False, True, False, True]

    def test_route_stripes_and_prefers_local(self):
        r = rm.ReplicaRouter(4, salt=0)
        rows = np.arange(16, dtype=np.int32)
        assert sorted(set(r.route(rows).tolist())) == [0, 1, 2, 3]
        pref = rm.ReplicaRouter(4, salt=0, preferred=2)
        assert set(pref.route(rows).tolist()) == {2}

    def test_dead_holder_routes_to_owner_sentinel(self):
        # A holder declared dead must not keep receiving striped rows:
        # route() returns -1 for its picks (partition falls back to the
        # owner) until a reply from it re-includes it.
        r = rm.ReplicaRouter(4, salt=0)
        rows = np.arange(16, dtype=np.int32)
        r.mark_dead(2)
        out = r.route(rows)
        assert 2 not in set(out.tolist())
        assert (out[rows % 4 == 2] == -1).all()
        r.mark_alive(2)
        assert 2 in set(r.route(rows).tolist())

    def test_empty_map_deactivates(self):
        r = rm.ReplicaRouter(2)
        r.apply(1, np.array([3], np.int32))
        assert r.active
        r.apply(2, np.empty(0, np.int32))
        assert not r.active
        assert not r.replicated_mask(np.array([3], np.int32)).any()


class TestReplicaStore:
    def _vals(self, rows, fill):
        return np.full((len(rows), 2), fill, np.float32)

    def test_sync_never_moves_backward(self):
        s = rm.ReplicaStore()
        rows = np.array([1, 2], np.int32)
        s.apply_sync(rows, self._vals(rows, 5.0), owner_sid=0, version=5)
        s.apply_sync(rows, self._vals(rows, 3.0), owner_sid=0, version=3)
        groups, keys, vals = s.serve(rows, 2, np.float32)
        assert groups == [(0, 5, 2)]
        np.testing.assert_array_equal(vals, self._vals(rows, 5.0))

    def test_watermark_recertifies_untouched_rows(self):
        # The defect the watermark exists for: a row pushed at version 2
        # and never touched by later Adds must not read as stale once the
        # owner's version advances — a flush that drained every dirty row
        # certifies ALL of the owner's entries at its version.
        s = rm.ReplicaStore()
        s.apply_sync(np.array([1], np.int32), self._vals([1], 1.0),
                     owner_sid=0, version=2)
        s.apply_sync(np.array([9], np.int32), self._vals([9], 4.0),
                     owner_sid=0, version=40, watermark=True)
        groups, _, _ = s.serve(np.array([1, 9], np.int32), 2, np.float32)
        assert groups == [(0, 40, 2)]  # floor = watermark, not 2

    def test_watermark_scoped_to_owner(self):
        s = rm.ReplicaStore()
        s.apply_sync(np.array([1], np.int32), self._vals([1], 1.0),
                     owner_sid=0, version=2)
        s.apply_sync(np.array([9], np.int32), self._vals([9], 4.0),
                     owner_sid=1, version=40, watermark=True)
        groups, _, _ = s.serve(np.array([1, 9], np.int32), 2, np.float32)
        assert (0, 2, 1) in groups and (1, 40, 1) in groups

    def test_seq_gap_drops_owner_entries(self):
        # A lost sync chunk (dead holder writer) must not be papered
        # over by a later watermark: the holder detects the per-owner
        # seq gap and drops that owner's entries before applying — the
        # dropped rows miss and repair instead of serving values a lost
        # refresh should have replaced.
        s = rm.ReplicaStore()
        s.apply_sync(np.array([1], np.int32), self._vals([1], 1.0),
                     owner_sid=0, version=2, seq=0)
        # seq 1 lost; seq 2 arrives with a watermark.
        s.apply_sync(np.array([9], np.int32), self._vals([9], 4.0),
                     owner_sid=0, version=40, watermark=True, seq=2)
        groups, keys, _ = s.serve(np.array([1, 9], np.int32), 2,
                                  np.float32)
        assert keys.tolist() == [9]  # row 1 dropped, not certified
        assert groups == [(0, 40, 1)]

    def test_seq_gap_scoped_to_owner(self):
        s = rm.ReplicaStore()
        s.apply_sync(np.array([1], np.int32), self._vals([1], 1.0),
                     owner_sid=0, version=2, seq=0)
        s.apply_sync(np.array([9], np.int32), self._vals([9], 4.0),
                     owner_sid=1, version=7, seq=5)  # other owner's gap
        _, keys, _ = s.serve(np.array([1, 9], np.int32), 2, np.float32)
        assert keys.tolist() == [1, 9]  # owner 0 untouched

    def test_redirty_refills_dirty_set(self):
        # The communicator's failure echo: lost chunk rows re-enter the
        # dirty set (promoted rows only) so the next flush re-pushes.
        set_flag("replica_hot_rows", 4)
        st = rm.ServerReplicaState(row_offset=0, my_rows=16)
        st.apply_map(1, np.array([2, 3], np.int32))
        st._dirty.clear()  # the initial push drained them
        st.redirty(np.array([2, 3, 9], np.int32))  # 9 not promoted
        assert st._dirty == {2, 3}

    def test_prune_and_missing_rows_absent(self):
        s = rm.ReplicaStore()
        rows = np.array([1, 2, 3], np.int32)
        s.apply_sync(rows, self._vals(rows, 1.0), owner_sid=0, version=1)
        s.prune_to(np.array([2], np.int32))
        groups, keys, _ = s.serve(np.array([1, 2, 3], np.int32), 2,
                                  np.float32)
        assert keys.tolist() == [2]
        assert groups == [(0, 1, 1)]
        assert len(s) == 1


class TestReplicaCoordinator:
    def _ingest(self, c, tid, rows, counts, reporter=0):
        return c.ingest(tid, np.asarray(rows, np.int32),
                        np.asarray(counts, np.int32), reporter=reporter)

    def test_promotes_above_threshold_only(self):
        set_flag("replica_hot_rows", 2)
        set_flag("replica_min_gets", 4)
        c = rm.ReplicaCoordinator()
        assert self._ingest(c, 0, [5, 6, 7], [10, 9, 1])
        assert c.promoted[0].tolist() == [5, 6]  # 7 below threshold

    def test_sticky_full_budget_no_eviction_by_noise(self):
        set_flag("replica_hot_rows", 2)
        set_flag("replica_min_gets", 4)
        c = rm.ReplicaCoordinator()
        self._ingest(c, 0, [5, 6], [10, 10])
        # A hotter challenger does not evict while incumbents stay warm:
        # boundary swaps cost a map broadcast + a full value push each.
        assert not self._ingest(c, 0, [5, 6, 8], [10, 10, 30], reporter=1)
        assert sorted(c.promoted[0].tolist()) == [5, 6]

    def test_demotion_when_cooled(self):
        set_flag("replica_hot_rows", 2)
        set_flag("replica_min_gets", 4)
        c = rm.ReplicaCoordinator()
        self._ingest(c, 0, [5, 6], [32, 32])
        # Same reporter again and again = new ROUND each time -> decay;
        # row 6 stops being reported and must eventually fall out.
        changed = False
        for _ in range(8):
            changed = self._ingest(c, 0, [5], [32]) or changed
        assert changed
        assert c.promoted[0].tolist() == [5]

    def test_round_decay_not_per_report(self):
        # 4 servers reporting once each is ONE round: counts must decay
        # once, not 4 times — a per-report decay would scale the decay
        # rate with the server count and crush every row toward the
        # threshold exactly on big clusters (the N=4 regression the
        # bench caught).
        set_flag("replica_hot_rows", 4)
        set_flag("replica_min_gets", 4)
        c = rm.ReplicaCoordinator()
        for rep in range(4):
            self._ingest(c, 0, [rep], [8], reporter=rep)
        assert all(v == 8.0 for v in c._counts[0].values())
        self._ingest(c, 0, [0], [8], reporter=0)  # round 2 begins
        assert c._counts[0][1] == 4.0  # decayed exactly once

    def test_budget_zero_disables(self):
        set_flag("replica_hot_rows", 0)
        c = rm.ReplicaCoordinator()
        assert not self._ingest(c, 0, [1], [100])
        assert c.promoted == {}


class TestReplicaMapWire:
    def test_pack_unpack_roundtrip(self):
        promoted = {0: np.array([1, 5], np.int32),
                    3: np.array([7], np.int32),
                    4: np.empty(0, np.int32)}
        blobs = rm.pack_replica_map(12, promoted)
        epoch, got = rm.unpack_replica_map(blobs)
        assert epoch == 12
        assert sorted(got) == [0, 3, 4]
        for tid in promoted:
            np.testing.assert_array_equal(got[tid], promoted[tid])

    def test_replica_slot_markers(self):
        msg = Message(src=0, dst=1, msg_type=MsgType.Reply_Get)
        assert replica_row_count(msg) == 0  # unmarked / legacy peer
        mark_replica_reply(msg, 0)
        assert replica_row_count(msg) == 0
        mark_replica_reply(msg, 17)
        assert replica_row_count(msg) == 17


class TestWaiterAddWaits:
    def test_extends_pending_count(self):
        w = Waiter(num_wait=1)
        w.add_waits(2)
        w.notify()
        w.notify()
        assert not w.wait(timeout=0.05)
        w.notify()
        assert w.wait(timeout=1.0)

    def test_completed_waiter_not_rearmed(self):
        w = Waiter(num_wait=1)
        w.notify()
        w.add_waits(3)  # abort/completion raced the repair: must drop
        assert w.wait(timeout=1.0)


class TestSamples:
    def test_percentiles_and_snapshot(self):
        s = Samples("t", cap=100)
        for v in range(1, 101):
            s.add(float(v))
        assert s.count == 100
        assert 45 <= s.percentile(50) <= 55
        snap = s.snapshot()
        assert snap["count"] == 100 and snap["max"] == 100.0
        assert snap["p50"] <= snap["p90"] <= snap["p99"] <= snap["max"]

    def test_ring_overwrite_bounds_memory(self):
        s = Samples("t2", cap=4)
        for v in range(100):
            s.add(float(v))
        assert len(s._buf) == 4
        assert s.count == 100
        assert s.percentile(0) >= 96.0  # only the newest cap retained


# ---------------------------------------------------------------------------
# property: 1-server vs N-server element-wise equivalence (satellite 2)
# ---------------------------------------------------------------------------

def _matrix_workload(num_row, num_col, sparse=False):
    """Deterministic add/get script touching every boundary row."""
    def body(rank):
        rng = np.random.default_rng(7)
        table = mv.create_matrix_table(num_row, num_col,
                                       is_sparse=sparse)
        if table is None:  # server-only rank: host the shard, then wait
            mv.current_zoo().barrier()
            return None
        outs = []
        for step in range(6):
            ids = np.unique(rng.integers(0, num_row, 12).astype(np.int32))
            table.add_rows(ids, rng.standard_normal(
                (ids.size, num_col)).astype(np.float32))
            # Boundary sweep: every shard edge and its neighbors, for
            # every POSSIBLE server count exercised by the test matrix
            # (off-by-one splits were the audit target).
            edge = []
            for n in (1, 2, 3, 4):
                for off in row_offsets(num_row, n):
                    edge.extend((off - 1, off, off + 1))
            edge = np.unique(np.clip(np.asarray(edge, np.int32), 0,
                                     num_row - 1))
            outs.append(table.get_rows(edge).copy())
            outs.append(table.get().copy())
        mv.current_zoo().barrier()
        return outs

    return body


def _run_sizes(body, sizes, argv=None):
    results = {}
    for n in sizes:
        roles = None if n == 1 else ["all"] + ["server"] * (n - 1)
        cluster = LocalCluster(n, argv=list(argv or []), roles=roles)
        cluster.timeout = 180.0
        results[n] = cluster.run(body)[0]
    return results


class TestShardEquivalence:
    @pytest.mark.parametrize("num_row", [16, 17, 3])
    def test_matrix_dense_1_vs_n(self, num_row):
        # 17 rows does not divide by 2 or 3 (remainder goes to the last
        # shard); 3 rows < 4 servers degenerates to one row per server.
        res = _run_sizes(_matrix_workload(num_row, 3), (1, 2, 3))
        for n in (2, 3):
            for a, b in zip(res[1], res[n]):
                np.testing.assert_allclose(a, b, rtol=0, atol=0,
                                           err_msg=f"n={n}")

    def test_matrix_dense_1_vs_n_with_replication(self):
        # Same equivalence with hot-shard replication ON: a single
        # worker's read-your-writes floor makes replica routing exact
        # for its own adds, so results must stay bit-identical.
        res = _run_sizes(
            _matrix_workload(16, 3), (1, 2, 3),
            argv=["-replica_hot_rows=8", "-replica_report_gets=4",
                  "-replica_min_gets=1", "-replica_sync_every=2"])
        for n in (2, 3):
            for a, b in zip(res[1], res[n]):
                np.testing.assert_allclose(a, b, rtol=0, atol=0,
                                           err_msg=f"n={n}")

    def test_matrix_sparse_1_vs_n(self):
        def body(rank):
            rng = np.random.default_rng(3)
            table = mv.create_matrix_table(10, 2, is_sparse=True)
            if table is None:
                mv.current_zoo().barrier()
                return None
            outs = [table.get().copy()]
            for _ in range(4):
                ids = np.unique(rng.integers(0, 10, 4).astype(np.int32))
                table.add_rows(ids, rng.standard_normal(
                    (ids.size, 2)).astype(np.float32))
                outs.append(table.get().copy())
            mv.current_zoo().barrier()
            return outs

        res = _run_sizes(body, (1, 2, 3))
        for n in (2, 3):
            for a, b in zip(res[1], res[n]):
                np.testing.assert_allclose(a, b, err_msg=f"n={n}")

    def test_array_1_vs_n(self):
        def body(rank):
            rng = np.random.default_rng(11)
            table = mv.create_array_table(13)  # 13 % 2, 13 % 3 != 0
            if table is None:
                mv.current_zoo().barrier()
                return None
            outs = []
            for _ in range(4):
                table.add(rng.standard_normal(13).astype(np.float32))
                outs.append(table.get().copy())
            mv.current_zoo().barrier()
            return outs

        res = _run_sizes(body, (1, 2, 3))
        for n in (2, 3):
            for a, b in zip(res[1], res[n]):
                np.testing.assert_allclose(a, b, err_msg=f"n={n}")

    def test_kv_1_vs_n(self):
        def body(rank):
            table = mv.create_kv_table()
            if table is None:
                mv.current_zoo().barrier()
                return None
            keys = np.array([0, 1, 7, 100, 101, 10**6], np.int64)
            for step in range(3):
                table.add(keys, np.arange(keys.size, dtype=np.float32)
                          + step)
            got = table.get(keys)
            mv.current_zoo().barrier()
            return sorted(got.items())

        res = _run_sizes(body, (1, 2, 3))
        assert res[1] == res[2] == res[3]


# ---------------------------------------------------------------------------
# integration: replica consistency (satellite 3)
# ---------------------------------------------------------------------------

_REPL_ARGS = ["-replica_hot_rows=8", "-replica_report_gets=4",
              "-replica_min_gets=1", "-replica_sync_every=2"]


def _drive_until(pred, table, ids, limit=400):
    for _ in range(limit):
        table.get_rows(ids)
        if pred():
            return True
    return False


class TestReplicaConsistency:
    # Topology note for all tests here: both ranks are worker+server
    # (LocalCluster default role "all"), so each rank's worker routes
    # replicated rows to its LOCAL shard. Head rows 0..k live in server
    # 0's range — rank 1 is therefore THE replica reader (its local
    # shard serves them from the replica store), and rank 1's own adds
    # to the head (acked by owner server 0) are exactly what the
    # read-your-writes floor must protect. Rank 0's head reads hit the
    # owner directly and are trivially fresh; a rank reading rows
    # another rank writes is only promised BOUNDED staleness, so a
    # passive reader asserts per-row monotonicity, not equality.
    def test_read_your_writes_and_hits(self):
        def body(rank):
            Dashboard.reset()
            table = mv.create_matrix_table(32, 4)
            base = np.arange(128, dtype=np.float32).reshape(32, 4)
            shadow = base.copy()
            if rank == 0:
                table.add(base.copy())
            mv.current_zoo().barrier()
            head = np.arange(6, dtype=np.int32)
            router = table._replica_router
            assert router is not None
            ok = _drive_until(lambda: router.active, table, head)
            mismatch = 0
            prev = None
            for step in range(60):
                got = table.get_rows(head)
                if rank == 1:
                    # The adder: read-your-writes makes every one of
                    # its reads exact, replica-served or repaired.
                    if not np.array_equal(got, shadow[head]):
                        mismatch += 1
                    if step % 10 == 0:
                        table.add_rows(head, np.ones((6, 4), np.float32))
                        shadow[head] += 1.0
                else:
                    # Passive reader: bounded staleness — values must
                    # never move BACKWARD (store version ordering).
                    if prev is not None and np.any(got < prev - 1e-6):
                        mismatch += 1
                    prev = got.copy()
            mv.current_zoo().barrier()
            hits = Dashboard.get(rm.REPLICA_HIT).count
            mv.current_zoo().barrier()
            return ok, mismatch, hits

        results = LocalCluster(2, argv=list(_REPL_ARGS)).run(body)
        assert all(r[0] for r in results), "promotion never happened"
        assert all(r[1] == 0 for r in results), \
            f"stale replica reads observed: {results}"
        # Replica stores actually served rows somewhere in the run.
        assert sum(r[2] for r in results) > 0

    def test_owner_bump_invalidates_stale_replica(self):
        # Between rank 1's Add ack (which raises its RYW floor) and the
        # owner's next write-through flush, rank 1's local replica rows
        # are BELOW the floor: its Get must repair to the owner (stale /
        # repair counters fire), never serve the pre-add value.
        def body(rank):
            Dashboard.reset()
            table = mv.create_matrix_table(32, 4)
            shadow = np.zeros((32, 4), np.float32)
            if rank == 0:
                table.add(np.zeros((32, 4), np.float32))
            mv.current_zoo().barrier()
            head = np.arange(4, dtype=np.int32)
            router = table._replica_router
            _drive_until(lambda: router.active, table, head)
            bad = 0
            for step in range(30):
                if rank == 1:
                    table.add_rows(head,
                                   np.full((4, 4), 1.0, np.float32))
                    shadow[head] += 1.0
                    got = table.get_rows(head)  # immediately post-add
                    if not np.array_equal(got, shadow[head]):
                        bad += 1
                else:
                    table.get_rows(head)
            mv.current_zoo().barrier()
            stale = Dashboard.get(rm.REPLICA_STALE).count
            repairs = Dashboard.get(rm.REPLICA_REPAIR).count
            mv.current_zoo().barrier()
            return bad, stale, repairs

        results = LocalCluster(2, argv=list(_REPL_ARGS)).run(body)
        assert all(r[0] == 0 for r in results), f"stale read: {results}"
        # The invalidation path actually fired somewhere in the run.
        assert sum(r[1] + r[2] for r in results) > 0

    def test_demotion_prunes_holder_store(self, env):
        # Server-side demotion: adopting a map that drops a row prunes
        # the holder's store entry (the worker stops routing on the same
        # epoch; a racing Get would miss and repair — never serve a
        # demoted ghost).
        set_flag("replica_hot_rows", 4)
        st = rm.ServerReplicaState(row_offset=16, my_rows=16)
        st.apply_map(1, np.array([2, 3], np.int32))  # foreign rows
        st.store.apply_sync(np.array([2, 3], np.int32),
                            np.ones((2, 2), np.float32), owner_sid=0,
                            version=1)
        assert len(st.store) == 2
        st.apply_map(2, np.array([2], np.int32))  # 3 demoted
        assert len(st.store) == 1
        _, keys, _ = st.store.serve(np.array([2, 3], np.int32), 2,
                                    np.float32)
        assert keys.tolist() == [2]

    def test_owner_promotion_pushes_initial_values(self):
        # MatrixServer.apply_replica_map on the OWNER must emit
        # Request_ReplicaSync messages carrying the CURRENT values of
        # newly promoted own rows toward every holder, chunked at
        # -replica_sync_rows with the watermark flag on the LAST chunk
        # only (an early-chunk watermark would certify rows still in
        # flight behind it).
        def body(rank):
            from multiverso_tpu.runtime import actor as actors
            table = mv.create_matrix_table(8, 2)
            base = np.arange(16, dtype=np.float32).reshape(8, 2)
            if rank == 0:
                table.add(base.copy())
            mv.current_zoo().barrier()
            if rank != 0:
                mv.current_zoo().barrier()
                return None
            srv = mv.current_zoo()._actors[actors.SERVER] \
                ._store[table.table_id]
            # Quiesced cluster: driving the server table from here
            # cannot race its actor (no requests are in flight).
            msgs = srv.apply_replica_map(
                epoch=5, rows=np.array([0, 1, 2, 42], np.int32))
            mv.current_zoo().barrier()
            return [(m.type_int, m.dst,
                     m.data[0].as_array(np.int32).tolist(),
                     m.data[1].as_array(np.float32).tolist(),
                     m.data[2].as_array(np.int32).tolist())
                    for m in msgs]

        args = ["-replica_hot_rows=4", "-replica_sync_rows=2"]
        msgs = LocalCluster(2, argv=args).run(body)[0]
        # Rows 0..2 are own (server 0 owns rows 0..3 of 8); 42 is out of
        # range and ignored by the own-row filter. 3 rows at cap 2 = 2
        # chunks, each to the single holder (rank 1 / server 1).
        assert len(msgs) == 2
        for type_int, dst, rows, vals, meta in msgs:
            assert type_int == int(MsgType.Request_ReplicaSync)
            assert dst == 1
            assert meta[0] == 0  # owner server id
            np.testing.assert_allclose(
                np.asarray(vals),
                np.arange(16, dtype=np.float32)[
                    np.repeat(np.asarray(rows), 2) * 2
                    + np.tile([0, 1], len(rows))])
        (r1, m1), (r2, m2) = [(m[2], m[4]) for m in msgs]
        assert r1 + r2 == [0, 1, 2]
        assert (m1[2], m2[2]) == (0, 1)  # watermark on the LAST chunk

    def test_sync_mode_disables_replication(self):
        def body(rank):
            table = mv.create_matrix_table(16, 2)
            active = table._replica_router is not None
            mv.current_zoo().barrier()
            return active

        results = LocalCluster(
            2, argv=["-sync=true"] + list(_REPL_ARGS)).run(body)
        assert results == [False, False]
