"""Collective tests: MA mode, allreduce engine, device-mesh psum.

Mirrors Test/test_allreduce.cpp:10-19 (ma-mode aggregate == world size) and
exercises the AllreduceEngine algorithms (Bruck allgather, recursive
halving) against numpy ground truth on 2..5 virtual ranks, plus the XLA
data-plane collectives on the 8-device CPU mesh.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.parallel import MAAverager, MASGDStep, \
    allreduce_mesh, model_average, model_average_async, pmean_mesh, \
    psum_scalar
from multiverso_tpu.runtime.allreduce_engine import AllreduceEngine
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.runtime.net import LocalFabric
from multiverso_tpu.util.dashboard import Dashboard


class TestAggregate:
    def test_ma_mode_aggregate_counts_world(self):
        # ref: Test/test_allreduce.cpp:10-19 — each rank contributes 1,
        # result == world size on every rank.
        def body(rank):
            out = mv.aggregate(np.array([1.0], np.float32))
            return float(out[0])

        assert LocalCluster(4, argv=["-ma=true"]).run(body) == [4.0] * 4

    def test_aggregate_sums_vectors(self):
        def body(rank):
            out = mv.aggregate(np.full(10, rank + 1.0))
            return out.tolist()

        for result in LocalCluster(3, argv=["-ma=true"]).run(body):
            assert result == [6.0] * 10

    def test_model_average(self):
        def body(rank):
            return model_average(np.full(4, float(rank)))[0]

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [0.5, 0.5]


class TestModelAverageAsync:
    def test_async_matches_sync_bit_identical(self):
        # The acceptance contract: with -allreduce_lossy off, the
        # overlapped average returns EXACTLY what the blocking one
        # does (same collective, same summation order).
        def body(rank):
            data = np.full(4096, float(rank + 1), np.float32)
            sync = model_average(data)
            fut = model_average_async(data)
            out = fut.result(timeout=60)
            np.testing.assert_array_equal(out, sync)
            return float(out[0])

        outs = LocalCluster(3, argv=["-ma=true"]).run(body)
        assert outs == [2.0] * 3

    def test_future_snapshots_input(self):
        # The caller may keep mutating its live buffer while the
        # average streams — the submitted values are a snapshot.
        def body(rank):
            data = np.full(2048, float(rank), np.float32)
            fut = model_average_async(data)
            data += 100.0  # must not leak into the collective
            return float(fut.result(timeout=60)[0])

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [0.5, 0.5]

    def test_averager_double_buffer_and_delta(self):
        # submit -> local progress -> collect(current): the result is
        # avg(snapshots) + local delta, and MA_COMM_STALL only charges
        # the residual blocked time.
        def body(rank):
            avg = MAAverager()
            params = np.full(1024, float(rank), np.float32)
            avg.submit(params)
            params += 2.0  # "training" while the average streams
            merged = avg.collect(current=params, timeout=60)
            # avg of (0,1) = 0.5; + local delta 2.0
            np.testing.assert_allclose(merged, np.full(1024, 2.5))
            with pytest.raises(RuntimeError):
                avg.collect()  # nothing in flight anymore
            return True

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [True] * 2

    def test_back_to_back_async_run_in_call_order(self):
        # FIFO ticketing: two async submissions (and a sync call mixed
        # in) must execute in CALL order on every rank, or same-
        # generation collectives cross-pair across ranks and silently
        # average A-data against B-data.
        def body(rank):
            a = model_average_async(
                np.full(2048, float(rank), np.float32))
            b = model_average_async(
                np.full(2048, float(rank * 10), np.float32))
            c = model_average(np.full(2048, float(rank * 100),
                              np.float32))
            return (float(a.result(timeout=60)[0]),
                    float(b.result(timeout=60)[0]), float(c[0]))

        outs = LocalCluster(2, argv=["-ma=true"]).run(body)
        assert outs == [(0.5, 5.0, 50.0)] * 2

    def test_submit_twice_refused(self):
        def body(rank):
            avg = MAAverager()
            avg.submit(np.ones(8, np.float32))
            try:
                avg.submit(np.ones(8, np.float32))
                return "missing-check"
            except RuntimeError:
                pass
            avg.collect(timeout=60)
            return "ok"

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == ["ok"] * 2

    def test_comm_stall_monitor_records(self):
        mon = Dashboard.get("MA_COMM_STALL")
        before = mon.count

        def body(rank):
            model_average(np.ones(64, np.float32))
            fut = model_average_async(np.ones(64, np.float32))
            fut.result(timeout=60)
            return True

        LocalCluster(2, argv=["-ma=true"]).run(body)
        # Every sync call + every blocked result() lands one sample.
        assert mon.count >= before + 2


class TestAllreduceEngine:
    @pytest.mark.parametrize("world", [2, 3, 4, 5])
    @pytest.mark.parametrize("count", [8, 5000])
    def test_allreduce_matches_numpy(self, world, count):
        # count=8 exercises the small/allgather path, 5000 the
        # reduce-scatter path (threshold 4KB, ref: engine.cpp:33).
        fabric = LocalFabric(world)
        rng = np.random.default_rng(0)
        inputs = [rng.standard_normal(count).astype(np.float64)
                  for _ in range(world)]
        expected = np.sum(inputs, axis=0)

        def body(rank):
            engine = AllreduceEngine(fabric.endpoint(rank))
            return engine.allreduce(inputs[rank])

        import threading
        results = [None] * world
        threads = [threading.Thread(
            target=lambda r=r: results.__setitem__(r, body(r)))
            for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "engine deadlocked"
        for out in results:
            np.testing.assert_allclose(out, expected, rtol=1e-10)

    def test_allgather_order(self):
        fabric = LocalFabric(3)
        import threading
        results = [None] * 3

        def body(rank):
            engine = AllreduceEngine(fabric.endpoint(rank))
            results[rank] = engine.allgather(
                np.array([float(rank)] * 2, np.float64))

        threads = [threading.Thread(target=body, args=(r,)) for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for gathered in results:
            assert [g[0] for g in gathered] == [0.0, 1.0, 2.0]


class TestMeshCollectives:
    def test_allreduce_mesh_sums_shards(self):
        import jax
        n = len(jax.devices())
        x = np.tile(np.arange(4, dtype=np.float32), (n, 1))
        out = np.asarray(allreduce_mesh(x))
        np.testing.assert_array_equal(out[0], n * np.arange(4))

    def test_psum_scalar_counts_devices(self):
        import jax
        assert psum_scalar(1.0) == len(jax.devices())

    def test_pmean_mesh(self):
        import jax
        n = len(jax.devices())
        x = np.stack([np.full(3, float(i)) for i in range(n)]).astype(
            np.float32)
        out = np.asarray(pmean_mesh(x))
        np.testing.assert_allclose(out[0], np.full(3, (n - 1) / 2))

    def test_ma_sgd_step_trains(self):
        # Linear regression y = 2x via MA data-parallel SGD on the mesh.
        import jax
        import jax.numpy as jnp
        n = len(jax.devices())

        def loss_fn(params, batch):
            x, y = batch[..., 0], batch[..., 1]
            pred = params["w"] * x
            return jnp.mean((pred - y) ** 2)

        rng = np.random.default_rng(1)
        params = {"w": jnp.zeros(())}
        step = MASGDStep(loss_fn, lr=0.1)
        for _ in range(60):
            x = rng.standard_normal((n * 16,)).astype(np.float32)
            batch = np.stack([x, 2 * x], axis=-1)
            params, loss = step(params, batch)
        assert abs(float(params["w"]) - 2.0) < 1e-2
        assert loss < 1e-3


class TestMAShardedAverager:
    """Delta-vs-last-average MA over the sharded collective
    (parallel/ma.py MAShardedAverager; docs/ALLREDUCE.md)."""

    def test_first_round_is_exact_mean_despite_divergence(self):
        # Round 1 has no reference: the delta IS the params, so the
        # result is the exact mean even though replicas already differ.
        from multiverso_tpu.parallel import MAShardedAverager

        def body(rank):
            av = MAShardedAverager()
            params = np.full(6000, float(rank + 1), np.float32)
            av.submit(params)
            out = av.collect()
            np.testing.assert_array_equal(
                out, np.full(6000, 1.5, np.float32))
            return True

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [True] * 2

    def test_reference_advances_and_bmuf_correction(self):
        # Round 2 ships only the delta vs the round-1 average; the
        # collected result is ref + mean(delta) + local progress made
        # while the average streamed.
        from multiverso_tpu.parallel import MAShardedAverager

        def body(rank):
            av = MAShardedAverager()
            params = np.full(5000, float(rank), np.float32)
            av.submit(params)
            ref1 = av.collect()           # mean(0, 1) = 0.5
            p2 = ref1 + (1.0 if rank == 0 else 3.0)
            av.submit(p2)
            p2_live = p2 + 0.25           # progress during the stream
            out = av.collect(current=p2_live)
            # ref2 = 0.5 + mean(1, 3) = 2.5; + local 0.25
            np.testing.assert_allclose(out, np.full(5000, 2.75))
            with pytest.raises(RuntimeError):
                av.collect()
            return True

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [True] * 2

    def test_sharded_model_average_matches_dense(self):
        from multiverso_tpu.parallel import (sharded_model_average,
                                             sharded_model_average_async)

        def body(rank):
            data = np.full(4096, float(rank + 1), np.float32)
            dense = model_average(data)
            sharded = sharded_model_average(data)
            np.testing.assert_array_equal(sharded, dense)
            fut = sharded_model_average_async(data)
            np.testing.assert_array_equal(fut.result(timeout=60),
                                          dense)
            return True

        assert LocalCluster(3, argv=["-ma=true"]).run(body) == [True] * 3

    def test_submit_while_busy_raises(self):
        from multiverso_tpu.parallel import MAShardedAverager

        def body(rank):
            av = MAShardedAverager()
            av.submit(np.zeros(2048, np.float32))
            try:
                with pytest.raises(RuntimeError):
                    av.submit(np.zeros(2048, np.float32))
            finally:
                av.collect(timeout=60)
            return True

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [True] * 2

    def test_engine_path_over_fabric_endpoints(self):
        # Drive the ENGINE's sharded path (LocalCluster's LocalNet
        # overrides it with the shared-memory fabric): a raw
        # NetInterface-default endpoint pair runs the real
        # reduce-scatter / shard-divide / allgather protocol.
        import threading
        import types
        from multiverso_tpu.parallel import MAShardedAverager
        from multiverso_tpu.runtime.tcp import TcpNet
        from multiverso_tpu.util.net_util import free_listen_port
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        nets = [TcpNet(r, eps) for r in range(2)]
        outs = [None, None]
        errs = [None, None]

        def body(rank):
            try:
                av = MAShardedAverager(
                    types.SimpleNamespace(net=nets[rank]))
                params = np.zeros(100000, np.float32)
                params[rank::97] = float(rank + 1)  # sparse delta shape
                av.submit(params)
                outs[rank] = av.collect()
            except BaseException as exc:  # noqa: BLE001
                errs[rank] = exc

        threads = [threading.Thread(target=body, args=(r,))
                   for r in range(2)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads), "hung"
            for exc in errs:
                if exc is not None:
                    raise exc
            np.testing.assert_array_equal(outs[0], outs[1])
            engine = nets[0]._allreduce_engine
            assert engine.last_algo == "sharded"
            assert engine.last_reduce_state_bytes <= 100000 * 4 / 2 + 64
        finally:
            for n in nets:
                n.finalize()
