"""Collective tests: MA mode, allreduce engine, device-mesh psum.

Mirrors Test/test_allreduce.cpp:10-19 (ma-mode aggregate == world size) and
exercises the AllreduceEngine algorithms (Bruck allgather, recursive
halving) against numpy ground truth on 2..5 virtual ranks, plus the XLA
data-plane collectives on the 8-device CPU mesh.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.parallel import MAAverager, MASGDStep, \
    allreduce_mesh, model_average, model_average_async, pmean_mesh, \
    psum_scalar
from multiverso_tpu.runtime.allreduce_engine import AllreduceEngine
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.runtime.net import LocalFabric
from multiverso_tpu.util.dashboard import Dashboard


class TestAggregate:
    def test_ma_mode_aggregate_counts_world(self):
        # ref: Test/test_allreduce.cpp:10-19 — each rank contributes 1,
        # result == world size on every rank.
        def body(rank):
            out = mv.aggregate(np.array([1.0], np.float32))
            return float(out[0])

        assert LocalCluster(4, argv=["-ma=true"]).run(body) == [4.0] * 4

    def test_aggregate_sums_vectors(self):
        def body(rank):
            out = mv.aggregate(np.full(10, rank + 1.0))
            return out.tolist()

        for result in LocalCluster(3, argv=["-ma=true"]).run(body):
            assert result == [6.0] * 10

    def test_model_average(self):
        def body(rank):
            return model_average(np.full(4, float(rank)))[0]

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [0.5, 0.5]


class TestModelAverageAsync:
    def test_async_matches_sync_bit_identical(self):
        # The acceptance contract: with -allreduce_lossy off, the
        # overlapped average returns EXACTLY what the blocking one
        # does (same collective, same summation order).
        def body(rank):
            data = np.full(4096, float(rank + 1), np.float32)
            sync = model_average(data)
            fut = model_average_async(data)
            out = fut.result(timeout=60)
            np.testing.assert_array_equal(out, sync)
            return float(out[0])

        outs = LocalCluster(3, argv=["-ma=true"]).run(body)
        assert outs == [2.0] * 3

    def test_future_snapshots_input(self):
        # The caller may keep mutating its live buffer while the
        # average streams — the submitted values are a snapshot.
        def body(rank):
            data = np.full(2048, float(rank), np.float32)
            fut = model_average_async(data)
            data += 100.0  # must not leak into the collective
            return float(fut.result(timeout=60)[0])

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [0.5, 0.5]

    def test_averager_double_buffer_and_delta(self):
        # submit -> local progress -> collect(current): the result is
        # avg(snapshots) + local delta, and MA_COMM_STALL only charges
        # the residual blocked time.
        def body(rank):
            avg = MAAverager()
            params = np.full(1024, float(rank), np.float32)
            avg.submit(params)
            params += 2.0  # "training" while the average streams
            merged = avg.collect(current=params, timeout=60)
            # avg of (0,1) = 0.5; + local delta 2.0
            np.testing.assert_allclose(merged, np.full(1024, 2.5))
            with pytest.raises(RuntimeError):
                avg.collect()  # nothing in flight anymore
            return True

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == [True] * 2

    def test_back_to_back_async_run_in_call_order(self):
        # FIFO ticketing: two async submissions (and a sync call mixed
        # in) must execute in CALL order on every rank, or same-
        # generation collectives cross-pair across ranks and silently
        # average A-data against B-data.
        def body(rank):
            a = model_average_async(
                np.full(2048, float(rank), np.float32))
            b = model_average_async(
                np.full(2048, float(rank * 10), np.float32))
            c = model_average(np.full(2048, float(rank * 100),
                              np.float32))
            return (float(a.result(timeout=60)[0]),
                    float(b.result(timeout=60)[0]), float(c[0]))

        outs = LocalCluster(2, argv=["-ma=true"]).run(body)
        assert outs == [(0.5, 5.0, 50.0)] * 2

    def test_submit_twice_refused(self):
        def body(rank):
            avg = MAAverager()
            avg.submit(np.ones(8, np.float32))
            try:
                avg.submit(np.ones(8, np.float32))
                return "missing-check"
            except RuntimeError:
                pass
            avg.collect(timeout=60)
            return "ok"

        assert LocalCluster(2, argv=["-ma=true"]).run(body) == ["ok"] * 2

    def test_comm_stall_monitor_records(self):
        mon = Dashboard.get("MA_COMM_STALL")
        before = mon.count

        def body(rank):
            model_average(np.ones(64, np.float32))
            fut = model_average_async(np.ones(64, np.float32))
            fut.result(timeout=60)
            return True

        LocalCluster(2, argv=["-ma=true"]).run(body)
        # Every sync call + every blocked result() lands one sample.
        assert mon.count >= before + 2


class TestAllreduceEngine:
    @pytest.mark.parametrize("world", [2, 3, 4, 5])
    @pytest.mark.parametrize("count", [8, 5000])
    def test_allreduce_matches_numpy(self, world, count):
        # count=8 exercises the small/allgather path, 5000 the
        # reduce-scatter path (threshold 4KB, ref: engine.cpp:33).
        fabric = LocalFabric(world)
        rng = np.random.default_rng(0)
        inputs = [rng.standard_normal(count).astype(np.float64)
                  for _ in range(world)]
        expected = np.sum(inputs, axis=0)

        def body(rank):
            engine = AllreduceEngine(fabric.endpoint(rank))
            return engine.allreduce(inputs[rank])

        import threading
        results = [None] * world
        threads = [threading.Thread(
            target=lambda r=r: results.__setitem__(r, body(r)))
            for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "engine deadlocked"
        for out in results:
            np.testing.assert_allclose(out, expected, rtol=1e-10)

    def test_allgather_order(self):
        fabric = LocalFabric(3)
        import threading
        results = [None] * 3

        def body(rank):
            engine = AllreduceEngine(fabric.endpoint(rank))
            results[rank] = engine.allgather(
                np.array([float(rank)] * 2, np.float64))

        threads = [threading.Thread(target=body, args=(r,)) for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for gathered in results:
            assert [g[0] for g in gathered] == [0.0, 1.0, 2.0]


class TestMeshCollectives:
    def test_allreduce_mesh_sums_shards(self):
        import jax
        n = len(jax.devices())
        x = np.tile(np.arange(4, dtype=np.float32), (n, 1))
        out = np.asarray(allreduce_mesh(x))
        np.testing.assert_array_equal(out[0], n * np.arange(4))

    def test_psum_scalar_counts_devices(self):
        import jax
        assert psum_scalar(1.0) == len(jax.devices())

    def test_pmean_mesh(self):
        import jax
        n = len(jax.devices())
        x = np.stack([np.full(3, float(i)) for i in range(n)]).astype(
            np.float32)
        out = np.asarray(pmean_mesh(x))
        np.testing.assert_allclose(out[0], np.full(3, (n - 1) / 2))

    def test_ma_sgd_step_trains(self):
        # Linear regression y = 2x via MA data-parallel SGD on the mesh.
        import jax
        import jax.numpy as jnp
        n = len(jax.devices())

        def loss_fn(params, batch):
            x, y = batch[..., 0], batch[..., 1]
            pred = params["w"] * x
            return jnp.mean((pred - y) ** 2)

        rng = np.random.default_rng(1)
        params = {"w": jnp.zeros(())}
        step = MASGDStep(loss_fn, lr=0.1)
        for _ in range(60):
            x = rng.standard_normal((n * 16,)).astype(np.float32)
            batch = np.stack([x, 2 * x], axis=-1)
            params, loss = step(params, batch)
        assert abs(float(params["w"]) - 2.0) < 1e-2
        assert loss < 1e-3
