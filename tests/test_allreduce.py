"""Allreduce engine tests: chunked ring, recursive halving, generation
tags, error-feedback lossy tiers, failure diagnostics, async transport.

Complements tests/test_collectives.py (which covers the ma-mode public
API and the device-mesh collectives): this file drives the engine
directly over LocalFabric virtual ranks and over real localhost TCP
endpoints, forcing each algorithm via ``-allreduce_algo``.
"""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.core.message import Message, MsgType
from multiverso_tpu.runtime.allreduce_engine import (AllreduceEngine,
                                                     choose_algo)
from multiverso_tpu.runtime.net import LocalFabric
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.net_util import free_listen_port


def run_ranks(engines, fn, timeout=60):
    """Run fn(rank, engine) on one thread per engine; returns results."""
    world = len(engines)
    results = [None] * world
    errors = [None] * world

    def body(rank):
        try:
            results[rank] = fn(rank, engines[rank])
        except BaseException as exc:  # noqa: BLE001 - reported below
            errors[rank] = exc

    threads = [threading.Thread(target=body, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
        assert not t.is_alive(), "engine deadlocked"
    for exc in errors:
        if exc is not None:
            raise exc
    return results


def fabric_engines(world):
    fabric = LocalFabric(world)
    return [AllreduceEngine(fabric.endpoint(r)) for r in range(world)]


def expected_reduce(inputs, reducer):
    out = inputs[0].copy()
    for part in inputs[1:]:
        out = reducer(out, part)
    return out


class TestRingAllreduce:
    @pytest.mark.parametrize("world", [2, 3, 4, 5, 6])
    @pytest.mark.parametrize("count", [8, 5000, 40003])
    def test_matches_numpy(self, world, count):
        # count=8 still routes through the small/Bruck path (forcing
        # ring only affects the large path); 40003 is indivisible by
        # every world size AND the chunk size, so both the chunk and
        # the per-chunk segment bounds are unequal.
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 16)  # force many chunks
        set_flag("allreduce_window", 2)
        engines = fabric_engines(world)
        rng = np.random.default_rng(0)
        inputs = [rng.standard_normal(count) for _ in range(world)]
        expected = np.sum(inputs, axis=0)
        results = run_ranks(engines,
                            lambda r, e: e.allreduce(inputs[r]))
        for out in results:
            np.testing.assert_allclose(out, expected, rtol=1e-10)

    @pytest.mark.parametrize("world", [3, 5])
    def test_other_reducer(self, world):
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 16)
        engines = fabric_engines(world)
        rng = np.random.default_rng(1)
        inputs = [rng.standard_normal(9001) for _ in range(world)]
        expected = expected_reduce(inputs, np.maximum)
        results = run_ranks(
            engines, lambda r, e: e.allreduce(inputs[r], np.maximum))
        for out in results:
            np.testing.assert_array_equal(out, expected)

    def test_shape_preserved(self):
        set_flag("allreduce_algo", "ring")
        engines = fabric_engines(3)
        inputs = [np.full((50, 40), float(r + 1)) for r in range(3)]
        results = run_ranks(engines,
                            lambda r, e: e.allreduce(inputs[r]))
        for out in results:
            assert out.shape == (50, 40)
            np.testing.assert_array_equal(out, np.full((50, 40), 6.0))

    def test_auto_prefers_ring_for_non_pow2(self):
        assert choose_algo(4 << 20, 1 << 20, 3) == "ring"
        assert choose_algo(32 * 1024, 8 * 1024, 3) == "ring"  # fold
        assert choose_algo(5000, 1250, 3) == "rhalving"

    def test_auto_prefers_rhalving_for_small_pow2(self):
        assert choose_algo(5000, 1250, 4) == "rhalving"
        assert choose_algo(4 << 20, 1 << 20, 4) == "ring"


class TestRecursiveHalving:
    @pytest.mark.parametrize("world", [3, 5, 6])
    @pytest.mark.parametrize("reducer", [np.add, np.maximum])
    def test_non_pow2_worlds(self, world, reducer):
        # The surplus-fold path, explicitly forced (auto would switch
        # non-pow2 worlds to the ring at these sizes).
        set_flag("allreduce_algo", "rhalving")
        engines = fabric_engines(world)
        rng = np.random.default_rng(2)
        inputs = [rng.standard_normal(5003) for _ in range(world)]
        expected = expected_reduce(inputs, reducer)
        results = run_ranks(
            engines, lambda r, e: e.allreduce(inputs[r], reducer))
        for out in results:
            np.testing.assert_allclose(out, expected, rtol=1e-10)

    def test_surplus_result_is_private(self):
        # The surplus rank's result must be its own buffer: in-process
        # the final frame is a reference to the leader's array, and a
        # caller mutating its result in place must not corrupt peers.
        set_flag("allreduce_algo", "rhalving")
        engines = fabric_engines(3)
        inputs = [np.full(2000, float(r + 1)) for r in range(3)]

        def body(rank, engine):
            out = engine.allreduce(inputs[rank])
            out += rank  # in-place mutation of the returned buffer
            return out

        results = run_ranks(engines, body)
        for rank, out in enumerate(results):
            np.testing.assert_array_equal(out, np.full(2000, 6.0 + rank))


class TestGenerationTags:
    def test_back_to_back_different_round_counts(self):
        # Regression: tags used to restart at fixed bases (1000/2000),
        # so consecutive allreduces whose round counts differ could
        # cross-match stash entries. The per-call generation in the
        # msg_id high bits makes every sequence safe; run a mix of
        # small (Bruck), ring, and rhalving payloads back to back on
        # persistent engines.
        set_flag("allreduce_algo", "auto")
        set_flag("allreduce_ring_kb", 16)
        set_flag("allreduce_chunk_kb", 16)
        world = 3
        engines = fabric_engines(world)
        rng = np.random.default_rng(3)
        for count in (6000, 41, 12000, 300, 9000, 8, 40000):
            inputs = [rng.standard_normal(count) for _ in range(world)]
            expected = np.sum(inputs, axis=0)
            results = run_ranks(engines,
                                lambda r, e: e.allreduce(inputs[r]))
            for out in results:
                np.testing.assert_allclose(out, expected, rtol=1e-10)

    def test_generation_in_msg_id_high_bits(self):
        engine = fabric_engines(2)[0]
        engine._gen = 5
        assert engine._mid(1000) == (5 << 20) | 1000


class TestFailureDiagnostics:
    def test_timeout_error_carries_context(self):
        # Peer never shows up: the error must name the peer, the tag,
        # the elapsed time, the flag to tune, and the stash state —
        # and must honor -allreduce_timeout_s instead of 120s.
        set_flag("allreduce_timeout_s", 0.3)
        fabric = LocalFabric(2)
        engine = AllreduceEngine(fabric.endpoint(0))
        start = time.monotonic()
        with pytest.raises(RuntimeError) as info:
            engine.allreduce(np.ones(8, np.float32))
        elapsed = time.monotonic() - start
        assert elapsed < 5.0, "flag-configured timeout not honored"
        text = str(info.value)
        for needle in ("peer 1", "msg_id", "allreduce_timeout_s",
                       "stash"):
            assert needle in text, (needle, text)

    def test_stash_cap_fails_loudly(self):
        # A crashed peer (or tag bug) flooding the endpoint with
        # unmatched frames must trip the cap, not grow the stash
        # unboundedly until the timeout.
        set_flag("allreduce_stash_cap", 8)
        set_flag("allreduce_timeout_s", 30.0)
        fabric = LocalFabric(2)
        junk_src = fabric.endpoint(1)
        for i in range(12):
            msg = Message(src=1, dst=0, msg_type=MsgType.Default,
                          msg_id=900000 + i)
            msg.push(np.zeros(4, np.float32))
            junk_src.send(msg)
        engine = AllreduceEngine(fabric.endpoint(0))
        start = time.monotonic()
        with pytest.raises(RuntimeError) as info:
            engine.allreduce(np.ones(8, np.float32))
        assert time.monotonic() - start < 5.0, "cap did not short-circuit"
        text = str(info.value)
        assert "stash exceeded 8" in text
        assert "allreduce_stash_cap" in text


class TestErrorFeedback:
    def _step_inputs(self, rng, world, n):
        # Bounded dynamic range so the int8 tier is eligible
        # (wire_codec._i8_fits) — the shape of normalized gradients.
        return [(np.sign(rng.standard_normal(n))
                 * rng.uniform(0.5, 1.5, n)).astype(np.float32)
                for _ in range(world)]

    def test_residual_corrected_lossy_tracks_lossless(self):
        # The EQuARX property: per-step quantization error is ~1%, but
        # with the residual carried across calls the ACCUMULATED sum
        # tracks the exact one — noise averages out instead of random-
        # walking. N=200000 fp32 with 64KB chunks puts every segment
        # over the 4KB codec floor, so the int8/f16 tiers engage.
        world, steps, n = 3, 20, 200000
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 64)
        set_flag("allreduce_lossy", True)
        engines = fabric_engines(world)
        rng = np.random.default_rng(7)
        acc = np.zeros(n, np.float64)
        exact = np.zeros(n, np.float64)
        per_step_rel = []
        for _ in range(steps):
            inputs = self._step_inputs(rng, world, n)
            step_exact = np.sum([x.astype(np.float64) for x in inputs],
                                axis=0)
            exact += step_exact
            results = run_ranks(engines,
                                lambda r, e: e.allreduce(inputs[r]))
            # Lossy results are still bit-identical across ranks: the
            # allgather forwards each owner's encoded frame verbatim
            # and the owner adopts its own decoded copy.
            for out in results[1:]:
                np.testing.assert_array_equal(out, results[0])
            acc += results[0].astype(np.float64)
            per_step_rel.append(
                float(np.abs(results[0] - step_exact).max()
                      / np.abs(step_exact).max()))
        assert engines[0]._ef, "lossy tiers never engaged"
        assert per_step_rel[0] > 1e-5, \
            "quantization inactive — the property test is vacuous"
        rel = float(np.abs(acc - exact).max() / np.abs(exact).max())
        # Residual-corrected: accumulated error stays ~one step's
        # quantization noise, far below steps * per-step error.
        assert rel < 0.02, (rel, per_step_rel)
        assert rel < 2 * max(per_step_rel), (rel, max(per_step_rel))

    def test_lossless_when_flag_off(self):
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 64)
        set_flag("allreduce_lossy", False)
        world, n = 2, 100000
        engines = fabric_engines(world)
        rng = np.random.default_rng(8)
        inputs = self._step_inputs(rng, world, n)
        expected = inputs[0] + inputs[1]
        results = run_ranks(engines,
                            lambda r, e: e.allreduce(inputs[r]))
        for out in results:
            np.testing.assert_array_equal(out, expected)
        assert not engines[0]._ef

    def test_non_add_reducer_stays_exact_under_lossy_flag(self):
        # Error feedback is an ADDITIVE identity: folding a carried
        # residual into a max-reduction would corrupt it, so a non-add
        # reducer must bypass the lossy tier entirely.
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 64)
        set_flag("allreduce_lossy", True)
        world, n = 3, 120000
        engines = fabric_engines(world)
        rng = np.random.default_rng(11)
        inputs = self._step_inputs(rng, world, n)
        expected = expected_reduce(inputs, np.maximum)
        results = run_ranks(
            engines, lambda r, e: e.allreduce(inputs[r], np.maximum))
        for out in results:
            np.testing.assert_array_equal(out, expected)
        assert not engines[0]._ef  # quantization never engaged

    def test_small_segments_fall_back_lossless(self):
        # Segments under the 4KB codec floor must ride exact even with
        # the lossy flag on (and consume any pending residual exactly).
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 4)  # segments ~1-2KB
        set_flag("allreduce_lossy", True)
        world, n = 3, 9000
        engines = fabric_engines(world)
        rng = np.random.default_rng(9)
        inputs = self._step_inputs(rng, world, n)
        expected = np.sum([x.astype(np.float64) for x in inputs],
                          axis=0).astype(np.float32)
        results = run_ranks(engines,
                            lambda r, e: e.allreduce(inputs[r]))
        for out in results:
            np.testing.assert_allclose(out, expected, atol=1e-5)


def sparse_inputs(rng, world, count, nnz, scale=1.0):
    """Per-rank sparse float32 blobs with exactly ``nnz`` nonzeros each
    (random support, bounded dynamic range so lossy tiers stay
    eligible)."""
    inputs = []
    for _ in range(world):
        x = np.zeros(count, np.float32)
        idx = rng.choice(count, nnz, replace=False)
        x[idx] = (np.sign(rng.standard_normal(nnz))
                  * rng.uniform(0.5, 1.5, nnz) * scale).astype(np.float32)
        inputs.append(x)
    return inputs


class TestChooseAlgo:
    """The ONE documented decision function: path pinned per
    (size, density, world) tuple — replacing the scattered byte-size
    checks (docs/ALLREDUCE.md algorithm-choice table)."""

    def test_small_payloads_always_bruck(self):
        for world in (2, 3, 8):
            assert choose_algo(4000, 1000, world) == "bruck"
            assert choose_algo(4000, 1000, world,
                               density=0.01) == "bruck"
            assert choose_algo(4000, 1000, world,
                               forced="sparse") == "bruck"
        # fewer elements than ranks: small path regardless of bytes
        assert choose_algo(40000, 5, 8) == "bruck"

    @pytest.mark.parametrize("world", [2, 3, 4, 5, 6])
    def test_sparse_picked_for_sparse_sums(self, world):
        assert choose_algo(8 << 20, 2 << 20, world,
                           density=0.05) == "sparse"

    def test_path_pinned_per_size_density_world(self):
        n = 2 << 20  # 8 MB fp32
        table = [
            # (nbytes, n_elems, world, density, expected)
            (8 << 20, n, 3, 0.05, "sparse"),
            (8 << 20, n, 3, 0.249, "sparse"),   # just below cutoff
            (8 << 20, n, 3, 0.251, "ring"),     # just above cutoff
            (8 << 20, n, 3, 0.9, "ring"),
            (8 << 20, n, 3, None, "ring"),      # no density signal
            (8 << 20, n, 4, 0.9, "ring"),
            (100 * 1024, 25600, 4, None, "rhalving"),
            (100 * 1024, 25600, 4, 0.01, "sparse"),
            (100 * 1024, 25600, 3, None, "ring"),  # non-pow2 fold
            (4000, 1000, 3, 0.01, "bruck"),
        ]
        for nbytes, n_elems, world, density, expected in table:
            got = choose_algo(nbytes, n_elems, world, density=density)
            assert got == expected, \
                (nbytes, n_elems, world, density, got, expected)

    def test_cutoff_clamped_to_codec_break_even(self):
        # -allreduce_sparse_density above the codec break-even is
        # meaningless (reduced segments would ride RAW): the effective
        # cutoff is min of the two.
        set_flag("allreduce_sparse_density", 0.6)
        set_flag("wire_codec_density", 0.3)
        assert choose_algo(8 << 20, 2 << 20, 3, density=0.29) == "sparse"
        assert choose_algo(8 << 20, 2 << 20, 3, density=0.31) == "ring"

    def test_index_budget_caps_sparse(self):
        set_flag("allreduce_sparse_idx_budget", 10000)
        # density 0.01 of 2M elements = 20971 union indices > budget
        assert choose_algo(8 << 20, 2 << 20, 3, density=0.01) == "ring"
        set_flag("allreduce_sparse_idx_budget", 30000)
        assert choose_algo(8 << 20, 2 << 20, 3, density=0.01) == "sparse"

    def test_non_add_or_non_f32_never_sparse(self):
        assert choose_algo(8 << 20, 2 << 20, 3, density=0.01,
                           reducer_is_add=False) == "ring"
        assert choose_algo(8 << 20, 1 << 20, 3, density=0.01,
                           is_f32=False) == "ring"
        # forcing sparse falls back to the ring for both
        assert choose_algo(8 << 20, 2 << 20, 3, reducer_is_add=False,
                           forced="sparse") == "ring"
        assert choose_algo(8 << 20, 1 << 20, 3, is_f32=False,
                           forced="sparse") == "ring"

    def test_forced_flags_win(self):
        set_flag("allreduce_algo", "rhalving")
        assert choose_algo(64 << 20, 16 << 20, 3, density=0.01) \
            == "rhalving"
        set_flag("allreduce_algo", "sparse")
        assert choose_algo(8 << 20, 2 << 20, 3) == "sparse"


class TestSparseAllreduce:
    @pytest.mark.parametrize("world", [2, 3, 4, 5, 6])
    @pytest.mark.parametrize("count", [40003, 150001])
    def test_index_union_reduce_matches_numpy(self, world, count):
        # Odd element counts: segment bounds and index streams are all
        # unequal; supports overlap partially (union ≠ any single
        # rank's support).
        set_flag("allreduce_algo", "sparse")
        engines = fabric_engines(world)
        rng = np.random.default_rng(13)
        inputs = sparse_inputs(rng, world, count, count // 25)
        expected = np.sum([x.astype(np.float64) for x in inputs],
                          axis=0)
        results = run_ranks(engines,
                            lambda r, e: e.allreduce(inputs[r]))
        assert engines[0].last_algo == "sparse"
        for out in results:
            assert out.dtype == np.float32
            np.testing.assert_allclose(out, expected, rtol=1e-5,
                                       atol=1e-5)
        # All ranks land on identical bytes.
        for out in results[1:]:
            np.testing.assert_array_equal(out, results[0])

    @pytest.mark.parametrize("world", [2, 3, 5])
    def test_bit_identical_to_unchunked_dense_ring(self, world):
        # The lossless contract that makes the switchover safe: the
        # sparse fold replays the unchunked ring's pairwise sums, so
        # the two paths agree BIT FOR BIT (docs/ALLREDUCE.md).
        count = 120000
        rng = np.random.default_rng(17)
        inputs = sparse_inputs(rng, world, count, count // 20)
        set_flag("allreduce_algo", "sparse")
        engines = fabric_engines(world)
        sparse = run_ranks(engines,
                           lambda r, e: e.allreduce(inputs[r]))
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 1 << 20)  # one chunk
        engines = fabric_engines(world)
        ring = run_ranks(engines, lambda r, e: e.allreduce(inputs[r]))
        for r in range(world):
            np.testing.assert_array_equal(sparse[r], ring[r])

    def test_switchover_boundary_picks_right_path(self):
        # Union density (sum of per-rank nnz / elements) just below the
        # cutoff rides sparse; just above rides the dense ring; both
        # produce the same answer (bit-equal to the unchunked ring).
        world, count = 2, 200000  # 800 KB fp32, cutoff 0.25
        rng = np.random.default_rng(19)
        set_flag("allreduce_algo", "auto")
        set_flag("allreduce_chunk_kb", 1 << 20)
        for per_rank_nnz, expected in ((24900, "sparse"),
                                       (25100, "ring")):
            inputs = sparse_inputs(rng, world, count, per_rank_nnz)
            engines = fabric_engines(world)
            auto = run_ranks(engines,
                             lambda r, e: e.allreduce(inputs[r]))
            assert engines[0].last_algo == expected, \
                (per_rank_nnz, engines[0].last_algo)
            set_flag("allreduce_algo", "ring")
            ring = run_ranks(fabric_engines(world),
                             lambda r, e: e.allreduce(inputs[r]))
            set_flag("allreduce_algo", "auto")
            for r in range(world):
                np.testing.assert_array_equal(auto[r], ring[r])

    def test_mixed_sparse_dense_generation_tags(self):
        # Back-to-back auto collectives alternating sparse (probe +
        # scatter + allgather bands) and dense (probe + ring bands)
        # payloads on PERSISTENT engines: stale frames from call g must
        # never cross-match call g+1 even across protocol shapes.
        set_flag("allreduce_algo", "auto")
        set_flag("allreduce_ring_kb", 16)
        set_flag("allreduce_chunk_kb", 16)
        world = 3
        engines = fabric_engines(world)
        rng = np.random.default_rng(23)
        seen = []
        for count, nnz in ((60000, 600), (41, 41), (120000, 120000),
                           (9000, 90), (200000, 1000), (8, 8)):
            if nnz == count:
                inputs = [rng.standard_normal(count).astype(np.float32)
                          for _ in range(world)]
            else:
                inputs = sparse_inputs(rng, world, count, nnz)
            expected = np.sum([x.astype(np.float64) for x in inputs],
                              axis=0)
            results = run_ranks(engines,
                                lambda r, e: e.allreduce(inputs[r]))
            seen.append(engines[0].last_algo)
            for out in results:
                np.testing.assert_allclose(out, expected, rtol=1e-4,
                                           atol=1e-4)
        assert "sparse" in seen and "bruck" in seen \
            and ("ring" in seen or "rhalving" in seen), seen

    def test_all_zero_input(self):
        # Density 0: every contribution is an empty index stream.
        set_flag("allreduce_algo", "sparse")
        engines = fabric_engines(3)
        inputs = [np.zeros(50000, np.float32) for _ in range(3)]
        results = run_ranks(engines,
                            lambda r, e: e.allreduce(inputs[r]))
        for out in results:
            np.testing.assert_array_equal(out,
                                          np.zeros(50000, np.float32))

    def test_fill_recorded_per_hop(self):
        from multiverso_tpu.util.dashboard import samples
        set_flag("allreduce_algo", "sparse")
        world, count = 3, 60000
        engines = fabric_engines(world)
        rng = np.random.default_rng(29)
        inputs = sparse_inputs(rng, world, count, 1200)
        reduce_fill = samples("SPARSE_FILL[reduce]")
        before = reduce_fill.count
        run_ranks(engines, lambda r, e: e.allreduce(inputs[r]))
        # one sample per folded stream per rank: world ranks x world
        # streams (the union can only grow hop over hop)
        assert reduce_fill.count - before == world * world
        recent = reduce_fill.export_recent(world * world)
        assert all(0.0 <= f <= 1.0 for f in recent)
        assert max(recent) <= 3 * 1200 * world / count

    def test_lossy_sparse_ef_convergence(self):
        # The EQuARX property on the SPARSE path: per-step quantization
        # error is visible, but with residuals carried across calls the
        # accumulated sum tracks the exact one.
        world, steps, count = 3, 20, 400000
        set_flag("allreduce_algo", "sparse")
        set_flag("allreduce_lossy", True)
        engines = fabric_engines(world)
        rng = np.random.default_rng(7)
        acc = np.zeros(count, np.float64)
        exact = np.zeros(count, np.float64)
        per_step_rel = []
        for _ in range(steps):
            inputs = sparse_inputs(rng, world, count, count // 20)
            step_exact = np.sum(
                [x.astype(np.float64) for x in inputs], axis=0)
            exact += step_exact
            results = run_ranks(engines,
                                lambda r, e: e.allreduce(inputs[r]))
            for out in results[1:]:
                np.testing.assert_array_equal(out, results[0])
            acc += results[0].astype(np.float64)
            per_step_rel.append(
                float(np.abs(results[0] - step_exact).max()
                      / np.abs(step_exact).max()))
        assert engines[0]._ef, "lossy tiers never engaged"
        assert per_step_rel[0] > 1e-6, \
            "quantization inactive — the property test is vacuous"
        rel = float(np.abs(acc - exact).max() / np.abs(exact).max())
        assert rel < 0.02, (rel, per_step_rel)
        assert rel < 2 * max(per_step_rel), (rel, max(per_step_rel))

    def test_sparse_over_tcp(self):
        set_flag("allreduce_algo", "sparse")
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(3)]
        from multiverso_tpu.runtime.tcp import TcpNet
        nets = [TcpNet(r, eps) for r in range(3)]
        try:
            engines = [AllreduceEngine(n) for n in nets]
            rng = np.random.default_rng(31)
            inputs = sparse_inputs(rng, 3, 150000, 3000)
            expected = np.sum([x.astype(np.float64) for x in inputs],
                              axis=0)
            results = run_ranks(engines,
                                lambda r, e: e.allreduce(inputs[r]),
                                timeout=90)
            for out in results:
                np.testing.assert_allclose(out, expected, rtol=1e-5,
                                           atol=1e-5)
            for out in results[1:]:
                np.testing.assert_array_equal(out, results[0])
        finally:
            for n in nets:
                n.finalize()


class TestShardedAverage:
    @pytest.mark.parametrize("world", [2, 3, 4, 5])
    def test_matches_mean(self, world):
        count = 90001
        engines = fabric_engines(world)
        rng = np.random.default_rng(37)
        inputs = sparse_inputs(rng, world, count, count // 30)
        expected = np.sum([x.astype(np.float64) for x in inputs],
                          axis=0) / world
        results = run_ranks(engines,
                            lambda r, e: e.sharded_average(inputs[r]))
        assert engines[0].last_algo == "sharded"
        for out in results:
            np.testing.assert_allclose(out, expected, rtol=1e-5,
                                       atol=1e-6)
        for out in results[1:]:
            np.testing.assert_array_equal(out, results[0])

    def test_bit_identical_to_ring_then_divide(self):
        # The acceptance contract: sharded (reduce-scatter, divide the
        # shard, allgather) equals the unchunked dense ring's
        # allreduce-then-divide BIT FOR BIT — same fold, same
        # elementwise divide, lossless transport in between.
        world, count = 3, 120000
        rng = np.random.default_rng(41)
        inputs = sparse_inputs(rng, world, count, count // 20)
        engines = fabric_engines(world)
        sharded = run_ranks(engines,
                            lambda r, e: e.sharded_average(inputs[r]))
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 1 << 20)
        engines = fabric_engines(world)
        dense = run_ranks(
            engines,
            lambda r, e: e.allreduce(inputs[r]) / world)
        for r in range(world):
            np.testing.assert_array_equal(sharded[r], dense[r])

    def test_reduce_state_is_one_segment(self):
        # The memory story: per-rank reduce state is ~1/world of the
        # buffer where the dense paths copy the whole flat buffer.
        world, count = 4, 200000
        engines = fabric_engines(world)
        rng = np.random.default_rng(43)
        inputs = sparse_inputs(rng, world, count, 2000)
        run_ranks(engines, lambda r, e: e.sharded_average(inputs[r]))
        for e in engines:
            assert e.last_reduce_state_bytes <= count * 4 / world + 64
        set_flag("allreduce_algo", "ring")
        run_ranks(engines, lambda r, e: e.allreduce(inputs[r]))
        assert engines[0].last_reduce_state_bytes == count * 4

    def test_small_payload_falls_back_to_bruck(self):
        engines = fabric_engines(3)
        inputs = [np.full(100, float(r + 1), np.float32)
                  for r in range(3)]
        results = run_ranks(engines,
                            lambda r, e: e.sharded_average(inputs[r]))
        for out in results:
            np.testing.assert_array_equal(out,
                                          np.full(100, 2.0, np.float32))

    def test_non_f32_raises(self):
        engine = fabric_engines(2)[0]
        with pytest.raises(TypeError):
            engine.sharded_average(np.zeros(10000, np.float64))

    def test_localnet_override_matches_fabric_mean(self):
        # LocalNet.sharded_average rides the shared-memory fabric (no
        # wire to save in-process): plain rank-ordered mean.
        fabric = LocalFabric(2)
        nets = [fabric.endpoint(r) for r in range(2)]
        inputs = [np.full(1000, float(r), np.float32) for r in range(2)]
        results = run_ranks(
            nets, lambda r, n: n.sharded_average(inputs[r]))
        for out in results:
            np.testing.assert_array_equal(out, np.full(1000, 0.5))

    def test_sharded_over_tcp_lossy(self):
        # Lossy sharded average over a real wire: ranks still land on
        # identical bytes (single-encode allgather forwards verbatim).
        set_flag("allreduce_lossy", True)
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        from multiverso_tpu.runtime.tcp import TcpNet
        nets = [TcpNet(r, eps) for r in range(2)]
        try:
            engines = [AllreduceEngine(n) for n in nets]
            rng = np.random.default_rng(47)
            inputs = sparse_inputs(rng, 2, 200000, 10000)
            expected = (inputs[0].astype(np.float64)
                        + inputs[1].astype(np.float64)) / 2
            results = run_ranks(
                engines, lambda r, e: e.sharded_average(inputs[r]),
                timeout=90)
            np.testing.assert_array_equal(results[0], results[1])
            np.testing.assert_allclose(results[0], expected, atol=0.02)
        finally:
            for n in nets:
                n.finalize()


class TestTcpAsyncTransport:
    def _pair(self):
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        from multiverso_tpu.runtime.tcp import TcpNet
        return [TcpNet(r, eps) for r in range(2)]

    def test_send_async_fifo_and_flush(self):
        a, b = self._pair()
        try:
            for i in range(40):
                msg = Message(src=0, dst=1, msg_type=MsgType.Default,
                              msg_id=i)
                msg.push(np.full(64, float(i), np.float32))
                a.send_async(msg)
            a.flush_sends(1, timeout=30)
            assert a.bytes_sent > 40 * 64 * 4
            got = [b.recv(timeout=10) for _ in range(40)]
            assert [m.msg_id for m in got] == list(range(40))
            np.testing.assert_array_equal(
                got[7].data[0].as_array(np.float32), np.full(64, 7.0))
        finally:
            a.finalize()
            b.finalize()

    def test_sync_send_ordered_after_async(self):
        # A blocking send must not overtake queued async frames.
        a, b = self._pair()
        try:
            for i in range(10):
                msg = Message(src=0, dst=1, msg_type=MsgType.Default,
                              msg_id=i)
                msg.push(np.zeros(50000, np.float32))  # non-trivial wire
                a.send_async(msg)
            tail = Message(src=0, dst=1, msg_type=MsgType.Default,
                           msg_id=99)
            tail.push(np.zeros(4, np.float32))
            a.send(tail)
            ids = [b.recv(timeout=10).msg_id for _ in range(11)]
            assert ids == list(range(10)) + [99]
        finally:
            a.finalize()
            b.finalize()

    def test_ring_allreduce_over_tcp(self):
        set_flag("allreduce_algo", "ring")
        set_flag("allreduce_chunk_kb", 64)
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(3)]
        from multiverso_tpu.runtime.tcp import TcpNet
        nets = [TcpNet(r, eps) for r in range(3)]
        try:
            engines = [AllreduceEngine(n) for n in nets]
            rng = np.random.default_rng(5)
            inputs = [rng.standard_normal(120000).astype(np.float32)
                      for _ in range(3)]
            expected = np.sum([x.astype(np.float64) for x in inputs],
                              axis=0)
            results = run_ranks(engines,
                                lambda r, e: e.allreduce(inputs[r]),
                                timeout=90)
            for out in results:
                np.testing.assert_allclose(out, expected, rtol=1e-4,
                                           atol=1e-4)
        finally:
            for n in nets:
                n.finalize()
