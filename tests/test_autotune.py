"""Closed-loop self-tuning tests (runtime/autotune.py, docs/AUTOTUNE.md).

Covers the dynamic-flag layer (util/configure.py TUNABLE_FLAGS +
apply hooks: hooks fire on broadcast with coerced values, non-tunable
flags are rejected atomically, config-epoch regression is ignored,
weakly-held hooks unregister with their owner), the Control_Config
broadcast/ack round trip through the communicator, the rejoin
re-anchor (a late-joining rank receives the current config epoch on
register), the AutotuneManager policies (SLO-gated staleness widening/
shrinking, hysteresis, cooldown, pinning, guardrail clamping), the
live retune of construction-time caches (row cache activation,
admission watermarks, batch window), and the ClusterMetrics ingest
hardening (out-of-order/stale report dropping keyed on incarnation +
sequence).
"""

import gc
import threading
import time

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import Message, MsgType
from multiverso_tpu.runtime import actor as actors
from multiverso_tpu.runtime.autotune import (AUTOTUNE_POLICIES,
                                             AutotuneManager)
from multiverso_tpu.util import configure
from multiverso_tpu.util.configure import (CANONICAL_FLAGS,
                                           TUNABLE_FLAGS, get_flag,
                                           register_tunable_hook,
                                           set_flag)
from multiverso_tpu.util.dashboard import METRIC_NAMES


@pytest.fixture
def env():
    mv.init([])
    yield
    mv.shutdown()


def _next_epoch(k: int = 1) -> int:
    """An epoch guaranteed to advance this process's applied
    watermark (the watermark is process-global and monotonic across
    tests)."""
    return configure.applied_config_epoch() + k


# ---------------------------------------------------------------------------
# The registries


class TestRegistries:
    def test_every_tunable_is_canonical(self):
        assert set(TUNABLE_FLAGS) <= set(CANONICAL_FLAGS)

    def test_every_policy_drives_a_tunable(self):
        assert set(AUTOTUNE_POLICIES) <= set(TUNABLE_FLAGS)

    def test_policy_metrics_are_canonical(self):
        from tools.mvlint.metric_lint import family_match
        for knob, policy in AUTOTUNE_POLICIES.items():
            for metric in policy["metrics"]:
                assert family_match(metric, METRIC_NAMES), \
                    (knob, metric)

    def test_policy_bounds_are_sane(self):
        for knob, policy in AUTOTUNE_POLICIES.items():
            assert policy["min"] <= policy["max"], knob
            default = CANONICAL_FLAGS[knob]
            assert policy["min"] <= default <= policy["max"], \
                (knob, default)


# ---------------------------------------------------------------------------
# The dynamic-flag layer


class TestDynamicFlagLayer:
    def test_register_hook_rejects_non_tunable(self):  # mvlint: ignore[tunable-lint]
        with pytest.raises(KeyError):  # the rejection under test
            register_tunable_hook("port", lambda v: None)

    def test_apply_tunable_fires_hook_with_coerced_value(self):
        seen = []
        register_tunable_hook("coalesce_max_msgs", seen.append)
        configure.apply_tunable("coalesce_max_msgs", "32")  # str in
        assert seen == [32]  # int out (canonical type coercion)
        assert get_flag("coalesce_max_msgs") == 32

    def test_apply_tunable_rejects_non_tunable(self):
        with pytest.raises(KeyError):
            configure.apply_tunable("port", 1234)

    def test_apply_config_epoch_regression_ignored(self):
        e = _next_epoch()
        assert configure.apply_config(
            e, {"coalesce_max_msgs": 16}) is True
        assert get_flag("coalesce_max_msgs") == 16
        # Same epoch replayed, and an older epoch: both no-ops.
        assert configure.apply_config(
            e, {"coalesce_max_msgs": 48}) is False
        assert configure.apply_config(
            e - 1, {"coalesce_max_msgs": 48}) is False
        assert get_flag("coalesce_max_msgs") == 16
        assert configure.applied_config_epoch() == e

    def test_apply_config_rejects_non_tunable_atomically(self):
        before = get_flag("coalesce_max_msgs")
        with pytest.raises(KeyError):
            configure.apply_config(_next_epoch(), {
                "coalesce_max_msgs": 8,   # tunable ...
                "port": 1234,             # ... but this is not
            })
        # NOTHING applied, watermark unmoved: a broadcast naming a
        # non-tunable flag is refused whole, never half-applied.
        assert get_flag("coalesce_max_msgs") == before

    def test_apply_config_rejects_bad_value_atomically(self):
        # A garbage VALUE (version skew / controller bug) must refuse
        # the whole update before the watermark moves, so a corrected
        # re-broadcast at the SAME epoch still lands.
        before = get_flag("coalesce_max_msgs")
        watermark = configure.applied_config_epoch()
        epoch = _next_epoch()
        with pytest.raises(ValueError):
            configure.apply_config(epoch, {
                "coalesce_max_msgs": 24,
                "max_get_staleness": "not-an-int"})
        assert get_flag("coalesce_max_msgs") == before
        assert configure.applied_config_epoch() == watermark
        # The epoch was not burned: the corrected broadcast applies.
        assert configure.apply_config(
            epoch, {"coalesce_max_msgs": 24}) is True
        assert get_flag("coalesce_max_msgs") == 24

    def test_weak_hook_unregisters_with_its_owner(self):
        fired = []

        class Owner:
            def hook(self, value):
                fired.append(value)

        owner = Owner()
        register_tunable_hook("coalesce_max_kb", owner.hook)
        configure.apply_tunable("coalesce_max_kb", 2048)
        assert fired == [2048]
        del owner
        gc.collect()
        configure.apply_tunable("coalesce_max_kb", 1024)
        assert fired == [2048]  # dead owner: hook silently pruned

    def test_bad_hook_does_not_block_the_rest(self):
        good = []

        def bad(value):
            raise RuntimeError("boom")

        register_tunable_hook("serving_batch_max_rows", bad)
        register_tunable_hook("serving_batch_max_rows", good.append)
        configure.apply_tunable("serving_batch_max_rows", 512)
        assert good == [512]


# ---------------------------------------------------------------------------
# Broadcast / ack / rejoin through the live runtime


def _config_msg(epoch: int, flags: dict, src=0, dst=0) -> Message:
    import json
    msg = Message(src=src, dst=dst, msg_type=MsgType.Control_Config)
    msg.push(Blob(np.frombuffer(
        json.dumps({"epoch": epoch, "flags": flags}).encode(),
        np.uint8).copy()))
    return msg


class TestConfigBroadcast:
    def test_broadcast_applies_and_acks(self, env):
        zoo = mv.current_zoo()
        controller = zoo._actors[actors.CONTROLLER]
        fired = []
        register_tunable_hook("max_get_staleness", fired.append)
        epoch = _next_epoch()
        zoo.send_to(actors.COMMUNICATOR,
                    _config_msg(epoch, {"max_get_staleness": 12}))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and controller.autotune.acked_epochs().get(0) != epoch:
            time.sleep(0.01)
        assert get_flag("max_get_staleness") == 12
        assert fired == [12]  # the apply hook fired on broadcast
        # The rank's ack reached the controller's convergence view.
        assert controller.autotune.acked_epochs()[0] == epoch

    def test_non_tunable_broadcast_rejected_but_acked(self, env):
        zoo = mv.current_zoo()
        controller = zoo._actors[actors.CONTROLLER]
        before = get_flag("max_get_staleness")
        watermark = configure.applied_config_epoch()
        epoch = _next_epoch(5)
        zoo.send_to(actors.COMMUNICATOR,
                    _config_msg(epoch, {"port": 9999,
                                        "max_get_staleness": 3}))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and 0 not in controller.autotune.acked_epochs():
            time.sleep(0.01)
        # Refused whole: flag untouched, watermark unmoved — and the
        # ack reports the UNCHANGED epoch so the controller can see
        # the rank not converging.
        assert get_flag("max_get_staleness") == before
        assert configure.applied_config_epoch() == watermark
        assert controller.autotune.acked_epochs()[0] == watermark

    def test_stale_broadcast_ignored_on_live_rank(self, env):
        zoo = mv.current_zoo()
        epoch = _next_epoch()
        zoo.send_to(actors.COMMUNICATOR,
                    _config_msg(epoch, {"client_cache_rows": 1024}))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and get_flag("client_cache_rows") != 1024:
            time.sleep(0.01)
        assert get_flag("client_cache_rows") == 1024
        # A reordered older broadcast must not roll the knob back.
        zoo.send_to(actors.COMMUNICATOR,
                    _config_msg(epoch - 1, {"client_cache_rows": 64}))
        time.sleep(0.3)
        assert get_flag("client_cache_rows") == 1024

    def test_rejoining_rank_receives_current_config_epoch(self, env):
        """The rejoin handshake re-anchors a restarted rank: after the
        controller's autotune has moved knobs, a late Control_Register
        (the rejoin path: _node_reply already frozen) must trigger a
        re-broadcast of the cumulative config at the CURRENT epoch."""
        zoo = mv.current_zoo()
        controller = zoo._actors[actors.CONTROLLER]
        mgr = controller.autotune
        # The controller moved a knob at some point in the past.
        mgr._config.update({"max_get_staleness": 7})
        mgr._epoch = _next_epoch(3)
        # A restarted rank re-registers (solo reply path).
        reg = Message(src=0, dst=0,
                      msg_type=MsgType.Control_Register)
        reg.push(Blob(np.array([0, 3, 0], np.int32)))
        controller.receive(reg)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and get_flag("max_get_staleness") != 7:
            time.sleep(0.01)
        assert get_flag("max_get_staleness") == 7
        assert configure.applied_config_epoch() == mgr.epoch
        # ... and the rank acked the re-broadcast epoch.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and mgr.acked_epochs().get(0) != mgr.epoch:
            time.sleep(0.01)
        assert mgr.acked_epochs()[0] == mgr.epoch
        # Drain the solo register reply the rejoin handshake parked in
        # the zoo mailbox, or the shutdown barrier would consume it.
        reply = zoo._pop_control()
        assert reply.type == MsgType.Control_Reply_Register


# ---------------------------------------------------------------------------
# Policies (pure evaluation over synthetic cluster views)


def _mgr(env_zoo) -> AutotuneManager:
    controller = env_zoo._actors[actors.CONTROLLER]
    return AutotuneManager(env_zoo, controller.metrics)


def _view(monitors=None, samples=None) -> dict:
    return {"v": 1, "ranks": {},
            "monitors_sum": monitors or {},
            "samples_merged": samples or {}}


def _gets(count, ms_per=0.5):
    return {"WORKER_PROCESS_GET": {"count": count,
                                   "elapsed_ms": count * ms_per},
            "SERVER_PROCESS_GET": {"count": count,
                                   "elapsed_ms": count * ms_per}}


class TestPolicies:
    def test_staleness_widens_inside_slo(self, env):
        mgr = _mgr(mv.current_zoo())
        # First view: no deltas yet -> every policy holds.
        assert mgr.evaluate(_view(monitors=_gets(1000))) == {}
        # Two consecutive widen verdicts (hysteresis) -> a change.
        assert mgr.evaluate(_view(monitors=_gets(2000))) == {}
        changes = mgr.evaluate(_view(monitors=_gets(3000)))
        assert changes.get("max_get_staleness") == 4
        assert mgr.gauges()["max_get_staleness"]["verdict"] == "up"

    def test_staleness_shrinks_on_slo_violation(self, env):
        set_flag("max_get_staleness", 16)
        mgr = _mgr(mv.current_zoo())
        slo_violating = {"SERVING_LATENCY_MS": {
            "count": 500, "p50": 10.0, "p90": 40.0,
            "p99": float(get_flag("autotune_slo_p99_ms")) * 2,
            "max": 500.0}}
        mgr.evaluate(_view(monitors=_gets(1000)))
        mgr.evaluate(_view(monitors=_gets(2000),
                           samples=slo_violating))
        changes = mgr.evaluate(_view(monitors=_gets(3000),
                                     samples=slo_violating))
        assert changes.get("max_get_staleness") == 8
        assert mgr.gauges()["max_get_staleness"]["verdict"] == "down"

    def test_idle_cluster_judges_nothing(self, env):
        mgr = _mgr(mv.current_zoo())
        for _ in range(4):
            assert mgr.evaluate(_view()) == {}
        assert mgr.gauges()["max_get_staleness"]["verdict"] == "idle"

    def test_hysteresis_needs_consecutive_verdicts(self, env):
        mgr = _mgr(mv.current_zoo())
        mgr.evaluate(_view(monitors=_gets(1000)))
        mgr.evaluate(_view(monitors=_gets(2000)))  # up #1
        assert mgr.evaluate(_view()) == {}          # idle resets
        mgr.evaluate(_view(monitors=_gets(3000)))   # up #1 again
        changes = mgr.evaluate(_view(monitors=_gets(4000)))  # up #2
        assert changes.get("max_get_staleness") == 4

    def test_cooldown_blocks_immediate_restep(self, env):
        mgr = _mgr(mv.current_zoo())
        mgr.evaluate(_view(monitors=_gets(1000)))
        mgr.evaluate(_view(monitors=_gets(2000)))
        assert "max_get_staleness" in \
            mgr.evaluate(_view(monitors=_gets(3000)))
        # Within the cooldown the knob holds even on an up verdict.
        assert mgr.evaluate(_view(monitors=_gets(4000))) == {}

    def test_pinned_knob_never_moves(self, env):
        set_flag("autotune_pin", "max_get_staleness")
        mgr = _mgr(mv.current_zoo())
        for i in range(5):
            assert mgr.evaluate(
                _view(monitors=_gets(1000 * (i + 1)))) == {}
        assert mgr.gauges()["max_get_staleness"]["verdict"] \
            == "pinned"

    def test_unpin_requires_fresh_hysteresis(self, env):
        mgr = _mgr(mv.current_zoo())
        mgr.evaluate(_view(monitors=_gets(1000)))
        mgr.evaluate(_view(monitors=_gets(2000)))  # up vote #1
        set_flag("autotune_pin", "max_get_staleness")
        mgr.evaluate(_view(monitors=_gets(3000)))  # pinned: streak
        set_flag("autotune_pin", "")               # must reset
        # One fresh up verdict must NOT complete the pre-pin streak.
        assert mgr.evaluate(_view(monitors=_gets(4000))) == {}
        changes = mgr.evaluate(_view(monitors=_gets(5000)))
        assert changes.get("max_get_staleness") == 4

    def test_operator_disabled_knob_stays_unmanaged(self, env):
        # -serving_batch_window_ms=0 means "batching disabled"
        # (docs/SERVING.md) — a value OUTSIDE the policy band. The
        # controller must never clamp it back in and re-enable what
        # the operator explicitly turned off.
        set_flag("serving_batch_window_ms", 0.0)
        mgr = _mgr(mv.current_zoo())
        deep = {"DISPATCH_QUEUE_DEPTH[d1]": {
            "count": 500, "p50": 50.0, "p90": 200.0, "p99": 400.0,
            "max": 500.0}}
        for i in range(5):
            changes = mgr.evaluate(
                _view(monitors=_gets(1000 * (i + 1)), samples=deep))
            assert "serving_batch_window_ms" not in changes
        assert get_flag("serving_batch_window_ms") == 0.0
        assert mgr.gauges()["serving_batch_window_ms"]["verdict"] \
            == "unmanaged"

    def test_guardrail_clamps_at_max(self, env):
        set_flag("max_get_staleness",
                 AUTOTUNE_POLICIES["max_get_staleness"]["max"])
        mgr = _mgr(mv.current_zoo())
        for i in range(5):
            changes = mgr.evaluate(
                _view(monitors=_gets(1000 * (i + 1))))
            assert "max_get_staleness" not in changes
        assert get_flag("max_get_staleness") \
            == AUTOTUNE_POLICIES["max_get_staleness"]["max"]

    def test_batch_window_backs_off_when_queues_deep(self, env):
        mgr = _mgr(mv.current_zoo())
        deep = {"DISPATCH_QUEUE_DEPTH[d1]": {
            "count": 500, "p50": 50.0, "p90": 200.0, "p99": 400.0,
            "max": 500.0}}
        mgr.evaluate(_view(monitors=_gets(1000), samples=deep))
        # The depth signal is window-based, not delta-based, so the
        # second consecutive deep view satisfies hysteresis.
        changes = mgr.evaluate(_view(monitors=_gets(2000),
                                     samples=deep))
        assert changes.get("serving_batch_window_ms") == 1.0

    def test_broadcast_refuses_non_tunable(self, env):
        mgr = _mgr(mv.current_zoo())
        with pytest.raises(KeyError):
            mgr._send_config(_next_epoch(), {"port": 1})

    def test_prometheus_gauges(self, env):
        mgr = _mgr(mv.current_zoo())
        mgr.evaluate(_view(monitors=_gets(1000)))
        mgr.note_ack(2, 7)
        text = mgr.prometheus_text()
        assert "mv_autotune_config_epoch" in text
        assert 'mv_autotune_value{knob="max_get_staleness"}' in text
        assert 'mv_autotune_verdict{knob=' in text
        assert 'mv_autotune_rank_epoch{rank="2"} 7' in text


# ---------------------------------------------------------------------------
# Live retune of construction-time caches


class TestLiveRetune:
    def test_row_cache_activates_and_deactivates(self, env):
        from multiverso_tpu.util.dashboard import Dashboard
        table = mv.create_matrix_table(32, 4)
        table.add(np.ones((32, 4), np.float32))
        ids = np.array([1, 2, 3], np.int32)
        gets = Dashboard.get("SERVER_PROCESS_GET")
        before = gets.count
        table.get_rows(ids)
        table.get_rows(ids)
        assert gets.count - before == 2  # inactive: pure pass-through
        configure.apply_tunable("max_get_staleness", 8)
        assert table._row_cache.active
        table.get_rows(ids)  # populates
        before = gets.count
        table.get_rows(ids)
        assert gets.count - before == 0  # served locally
        configure.apply_tunable("max_get_staleness", 0)
        assert not table._row_cache.active
        assert not table._row_cache._rows  # deactivation clears
        before = gets.count
        table.get_rows(ids)
        assert gets.count - before == 1  # back to pass-through

    def test_ryw_holds_across_live_widening(self, env):
        table = mv.create_matrix_table(16, 2)
        configure.apply_tunable("max_get_staleness", 32)
        ids = np.array([3, 5], np.int32)
        for k in range(1, 6):
            table.add_rows(ids, np.ones((2, 2), np.float32))
            got = table.get_rows(ids)
            np.testing.assert_allclose(got, float(k))
        configure.apply_tunable("max_get_staleness", 0)

    def test_activation_edge_ryw_fence(self):
        """The nasty interleaving: a Get reply served BEFORE an own
        add is still in flight when the cache activates; it lands
        after activation carrying the pre-add version. The add's ack
        fence (recorded while the cache was inactive) must keep that
        value from ever serving — read-your-writes across the
        activation edge."""
        from multiverso_tpu.tables.client_cache import (RowCache,
                                                        VersionTracker)
        tracker = VersionTracker()
        cache = RowCache(0, lambda rows: np.zeros(len(rows), np.int64),
                         1, tracker)
        # Inactive: the in-flight own add takes a fence token.
        token = cache.begin_add(np.array([5], np.int64))
        assert token[0] == "fence"
        cache._retune_bound(8)  # live activation (Control_Config)
        # The delayed pre-add reply lands and stores at version 3 ...
        tracker.note(0, 3)
        cache.store(np.array([5]), np.ones((1, 4), np.float32), 3, 0)
        # ... then the add acks at version 4 and the fence fires.
        tracker.note(0, 4)
        cache.finish_add(token)
        out = np.zeros((1, 4), np.float32)
        missing = cache.fetch_into(np.array([5], np.int64), out)
        assert missing.size == 1, \
            "pre-add value served after the acked write (RYW)"

    def test_row_cache_capacity_retune_evicts(self, env):
        configure.apply_tunable("max_get_staleness", 8)
        table = mv.create_matrix_table(64, 2)
        table.add(np.ones((64, 2), np.float32))
        table.get_rows(np.arange(32, dtype=np.int32))
        assert len(table._row_cache._rows) == 32
        configure.apply_tunable("client_cache_rows", 8)
        assert len(table._row_cache._rows) <= 8
        configure.apply_tunable("max_get_staleness", 0)

    def test_admission_watermarks_retune_live(self, env):
        from multiverso_tpu.serving.admission import \
            AdmissionController
        ac = AdmissionController()
        assert ac.stats()["max_inflight"] == 64
        configure.apply_tunable("serving_max_inflight", 2)
        configure.apply_tunable("serving_shed_depth", 17)
        assert ac.stats()["max_inflight"] == 2
        assert ac.stats()["shed_depth"] == 17

    def test_worker_coalesce_caps_retune_live(self, env):
        zoo = mv.current_zoo()
        worker = zoo._actors.get(actors.WORKER)
        assert worker._max_batch_msgs == 64
        configure.apply_tunable("coalesce_max_msgs", 16)
        configure.apply_tunable("coalesce_max_kb", 128)
        assert worker._max_batch_msgs == 16
        assert worker._max_batch_bytes == 128 << 10


# ---------------------------------------------------------------------------
# ClusterMetrics ingest hardening


def _report(rank, seq, inc="inc-a", value=1):
    return {"v": 1, "rank": rank, "inc": inc, "seq": seq,
            "monitors": {"X": {"count": value, "elapsed_ms": 0.0}},
            "samples": {}, "trace_events": []}


class TestIngestHardening:
    def _metrics(self):
        from multiverso_tpu.runtime.metrics import ClusterMetrics
        return ClusterMetrics()

    def test_out_of_order_report_dropped(self):
        cm = self._metrics()
        cm.ingest(_report(1, seq=5, value=50))
        cm.ingest(_report(1, seq=4, value=40))  # late frame: dropped
        cm.ingest(_report(1, seq=5, value=99))  # replay: dropped
        view = cm.cluster_view()
        assert view["monitors_sum"]["X"]["count"] == 50
        assert view["dropped_reports"] == 2

    def test_new_incarnation_resets_the_watermark(self):
        cm = self._metrics()
        cm.ingest(_report(1, seq=9, inc="inc-a", value=90))
        # The rank restarted/rejoined: its reporter starts from seq 1
        # under a fresh incarnation — MUST fold, not drop.
        cm.ingest(_report(1, seq=1, inc="inc-b", value=7))
        view = cm.cluster_view()
        assert view["monitors_sum"]["X"]["count"] == 7
        assert view["dropped_reports"] == 0

    def test_superseded_incarnation_dropped(self):
        # A de-parked PRE-CRASH frame arriving after the restarted
        # rank already reported must not roll the view back to the
        # dead process (or reset the watermark under it).
        cm = self._metrics()
        cm.ingest(_report(1, seq=500, inc="inc-a", value=500))
        cm.ingest(_report(1, seq=1, inc="inc-b", value=1))
        cm.ingest(_report(1, seq=2, inc="inc-b", value=2))
        cm.ingest(_report(1, seq=500, inc="inc-a", value=500))
        view = cm.cluster_view()
        assert view["monitors_sum"]["X"]["count"] == 2
        assert view["dropped_reports"] == 1
        # ... and the live incarnation keeps advancing normally.
        cm.ingest(_report(1, seq=3, inc="inc-b", value=3))
        assert cm.cluster_view()["monitors_sum"]["X"]["count"] == 3

    def test_prior_incarnation_cap_evicts_oldest(self):
        # The cap must evict the OLDEST superseded incarnation: the
        # most recent predecessor's de-parked frames are exactly the
        # ones the guard exists to drop.
        cm = self._metrics()
        n = cm._PRIOR_INC_CAP + 2
        for i in range(n):
            cm.ingest(_report(1, seq=1, inc=f"inc-{i}", value=i))
        cm.ingest(_report(1, seq=999, inc=f"inc-{n - 2}", value=999))
        view = cm.cluster_view()
        assert view["dropped_reports"] == 1
        assert view["monitors_sum"]["X"]["count"] == n - 1

    def test_legacy_reports_without_seq_always_fold(self):
        cm = self._metrics()
        payload = _report(1, seq=None, value=3)
        del payload["seq"], payload["inc"]
        cm.ingest(payload)
        cm.ingest(payload)
        assert cm.cluster_view()["monitors_sum"]["X"]["count"] == 3
        assert cm.cluster_view()["dropped_reports"] == 0

    def test_reporter_stamps_monotonic_seq(self, env):
        zoo = mv.current_zoo()
        from multiverso_tpu.runtime.metrics import MetricsReporter
        reporter = MetricsReporter(zoo)
        controller = zoo._actors[actors.CONTROLLER]
        reporter.flush()
        reporter.flush()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            mark = controller.metrics._report_mark.get(0)
            if mark is not None and mark[1] >= 2:
                break
            time.sleep(0.01)
        mark = controller.metrics._report_mark[0]
        assert mark[0] == reporter._incarnation
        assert mark[1] == 2
