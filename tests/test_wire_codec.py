"""Wire codec: round-trip properties, golden header bytes, negotiation,
error feedback, and batch-add coalescing framing.

Tier-1 (fast, host-only) coverage for the compact wire format — codec
regressions fail here instead of only showing up as a bench-phase drift.
"""

import numpy as np
import pytest

from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import (CODEC_SLOT, Message, MsgType,
                                         pack_add_batch, unpack_add_batch)
from multiverso_tpu.util import wire_codec as wc


def _power_law_blob(n=65536, nnz=1024, seed=0):
    rng = np.random.default_rng(seed)
    blob = np.zeros(n, np.float32)
    idx = np.sort(rng.choice(n, nnz, replace=False))
    blob[idx] = ((rng.pareto(2.0, nnz) + 0.1)
                 * np.sign(rng.standard_normal(nnz))).astype(np.float32)
    return blob


BLOBS = {
    "empty": np.zeros(0, np.float32),
    "all_zero": np.zeros(4096, np.float32),
    "fully_dense": np.arange(1, 513, dtype=np.float32),
    "power_law_sparse": _power_law_blob(),
    # Magnitudes past fp16's max finite (65504): the fp16 tiers must be
    # ruled out by the dynamic-range heuristic, never overflow to inf.
    "fp16_overflow": np.where(np.arange(2048) % 64 == 0,
                              1.0e5, 0.0).astype(np.float32),
    "single_nnz": np.eye(1, 300, 42, dtype=np.float32).reshape(-1),
    "wide_gap": np.bincount([0, 150000], weights=[1.0, -2.0],
                            minlength=200000).astype(np.float32),
}


class TestRoundTrip:
    @pytest.mark.parametrize("name", sorted(BLOBS))
    def test_lossless_exact(self, name):
        blob = BLOBS[name]
        frame, residual = wc.encode_blob(blob)
        assert residual is None  # lossless tiers carry no residual
        out = wc.decode_blob(frame)
        assert out.dtype == blob.dtype
        np.testing.assert_array_equal(out, blob)

    @pytest.mark.parametrize("name", sorted(BLOBS))
    def test_lossy_bounded(self, name):
        blob = BLOBS[name]
        frame, residual = wc.encode_blob(blob, lossy=True)
        out = wc.decode_blob(frame)
        assert np.all(np.isfinite(out)), "lossy tier overflowed"
        if residual is None:
            np.testing.assert_array_equal(out, blob)
        else:
            # decoded + residual == original: the residual is exactly
            # the information the wire dropped.
            np.testing.assert_allclose(out + residual, blob, rtol=0,
                                       atol=1e-5)

    @pytest.mark.parametrize("tier_floats", [
        np.zeros(100, np.float32),                          # sparse empty
        _power_law_blob(4096, 64, seed=1),                  # sparse f32/f16/i8
        np.linspace(-1, 1, 4096, dtype=np.float32),         # dense f16/i8
        np.linspace(-1e5, 1e5, 4096, dtype=np.float32),     # fp16-ineligible
    ])
    def test_every_lossy_choice_reversible(self, tier_floats):
        frame, residual = wc.encode_blob(tier_floats, lossy=True)
        out = wc.decode_blob(frame)
        ref = tier_floats if residual is None else tier_floats - residual
        np.testing.assert_allclose(out, ref, rtol=0, atol=1e-4)

    def test_nan_and_inf_survive_every_mode(self):
        # NaN compares False against the clip threshold: a naive
        # magnitude test would drop a diverging trainer's NaN gradients
        # and deliver ZEROS, masking the divergence. Non-finite slots
        # must ride the index stream and come back bit-identical, in
        # both lossless and lossy modes (where they also disqualify the
        # fp16/int8 tiers).
        blob = _power_law_blob(4096, 64, seed=7)
        blob[100] = np.nan
        blob[200] = np.inf
        blob[300] = -np.inf
        for lossy in (False, True):
            frame, residual = wc.encode_blob(blob, lossy=lossy)
            out = wc.decode_blob(frame)
            assert residual is None  # lossy tiers must opt out
            np.testing.assert_array_equal(out, blob)

    def test_non_float32_rides_raw(self):
        for arr in (np.arange(7, dtype=np.int64),
                    np.frombuffer(b"option blob bytes", np.uint8),
                    np.array([1.5, 0.0, 2.5], np.float64)):
            frame, residual = wc.encode_blob(arr, lossy=True)
            assert wc.peek_tier(frame) == wc.RAW
            assert residual is None
            out = wc.decode_blob(frame)
            assert out.dtype == arr.dtype
            np.testing.assert_array_equal(out, arr)

    def test_fp16_overflow_never_picks_fp16(self):
        frame, _ = wc.encode_blob(BLOBS["fp16_overflow"], lossy=True)
        assert wc.peek_tier(frame) not in (wc.SPARSE_F16, wc.DENSE_F16)

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError, match="magic"):
            wc.decode_blob(np.zeros(64, np.uint8))

    def test_is_codec_frame_sniff(self):
        frame, _ = wc.encode_blob(_power_law_blob(1024, 16, seed=9))
        assert wc.is_codec_frame(frame)
        # Raw float32 values, short buffers, and near-miss headers all
        # sniff negative — receivers fall back to the raw layout.
        assert not wc.is_codec_frame(
            np.linspace(0, 1, 256, dtype=np.float32))
        assert not wc.is_codec_frame(np.zeros(8, np.uint8))
        broken = bytearray(frame)
        broken[3] = 99  # unknown tier
        assert not wc.is_codec_frame(bytes(broken))


class TestCompressionRatio:
    def test_beats_old_float64_pairs_on_sparse_gradient(self):
        # CI gate for the headline claim: a canned power-law sparse
        # gradient must shrink vs BOTH the removed float64-pair format
        # (16 B/pair + 8 B size record) and the raw dense bytes.
        blob = _power_law_blob(1 << 18, (1 << 18) // 20, seed=3)
        nnz = int(np.count_nonzero(blob))
        old_bytes = 16 * nnz + 8
        frame, _ = wc.encode_blob(blob)
        assert old_bytes / len(frame) > 2.0, (old_bytes, len(frame))
        assert blob.nbytes / len(frame) > 1.0
        lossy_frame, _ = wc.encode_blob(blob, lossy=True)
        assert len(lossy_frame) < len(frame)

    def test_dense_blob_costs_only_header(self):
        dense = np.arange(1, 4097, dtype=np.float32)
        frame, _ = wc.encode_blob(dense)
        assert len(frame) == wc.HEADER_BYTES + dense.nbytes


class TestGoldenHeader:
    def test_header_layout_stable(self):
        # Golden bytes: the on-wire header of a known blob. Any change
        # here is a WIRE FORMAT BREAK — bump VERSION and update
        # docs/WIRE_FORMAT.md, don't just fix the test.
        blob = np.zeros(256, np.float32)
        blob[[3, 10]] = [1.0, -2.0]
        frame, _ = wc.encode_blob(blob)
        assert frame[:24] == (
            b"MV"                       # magic
            b"\x01"                     # version
            b"\x01"                     # tier = SPARSE_F32
            b"\x00"                     # dtype = float32
            b"\x01"                     # idx encoding = u16 gaps
            b"\x00\x00"                 # chunk (unused for f32)
            b"\x00\x01\x00\x00\x00\x00\x00\x00"   # n = 256
            b"\x02\x00\x00\x00\x00\x00\x00\x00")  # nnz = 2
        # Payload: first idx u32(3), gap u16(7), two fp32 values.
        assert frame[24:] == (b"\x03\x00\x00\x00" b"\x07\x00"
                              + np.array([1.0, -2.0], np.float32).tobytes())

    def test_raw_header_stable(self):
        frame, _ = wc.encode_blob(np.arange(3, dtype=np.int32))
        assert frame[:8] == b"MV\x01\x00\x02\x00\x00\x00"
        assert frame[8:24] == (3).to_bytes(8, "little") * 2


class TestErrorFeedback:
    def test_residual_fold_bounds_accumulated_error(self):
        # OneBitFilter-style error feedback: folding the residual into
        # the next delta keeps the ACCUMULATED decoded sum within one
        # quantization step of the true sum, instead of drifting by
        # O(steps) * step.
        rng = np.random.default_rng(11)
        n, nnz, steps = 1 << 14, 1 << 9, 25
        idx = np.sort(rng.choice(n, nnz, replace=False))
        true_sum = np.zeros(n, np.float64)
        fed_sum = np.zeros(n, np.float64)
        naive_sum = np.zeros(n, np.float64)
        residual = np.zeros(n, np.float32)
        one_step_err = 0.0
        for _ in range(steps):
            g = np.zeros(n, np.float32)
            g[idx] = rng.standard_normal(nnz).astype(np.float32)
            true_sum += g
            frame, res = wc.encode_blob(g + residual, lossy=True)
            residual = res if res is not None \
                else np.zeros(n, np.float32)
            fed_sum += wc.decode_blob(frame)
            nf, nres = wc.encode_blob(g, lossy=True)
            naive_sum += wc.decode_blob(nf)
            if nres is not None:
                one_step_err = max(one_step_err,
                                   float(np.abs(nres).max()))
        fed_err = float(np.abs(fed_sum - true_sum).max())
        naive_err = float(np.abs(naive_sum - true_sum).max())
        assert fed_err <= one_step_err * 2 + 1e-5, (fed_err, one_step_err)
        assert fed_err < naive_err  # feedback strictly beats drift


class TestMessageFilter:
    def _msg(self, *arrays):
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                      table_id=0, msg_id=5)
        for arr in arrays:
            msg.push(Blob(arr))
        return msg

    def test_message_roundtrip_mixed_blobs(self):
        keys = np.arange(64, dtype=np.int32)
        vals = _power_law_blob(1 << 15, 200, seed=5)
        opt = np.frombuffer(b"\x01\x02" * 24, np.uint8).copy()
        msg = self._msg(keys.view(np.uint8), vals, opt)
        assert wc.encode_message(msg)
        assert msg.header[CODEC_SLOT] == 1
        wire = sum(b.size for b in msg.data)
        assert wire < keys.nbytes + vals.nbytes + opt.nbytes
        wc.decode_message(msg)
        assert msg.header[CODEC_SLOT] == 0
        np.testing.assert_array_equal(
            msg.data[0].as_array(np.int32), keys)
        np.testing.assert_array_equal(
            msg.data[1].as_array(np.float32), vals)
        np.testing.assert_array_equal(msg.data[2].as_array(np.uint8), opt)

    def test_small_messages_pass_through(self):
        msg = self._msg(np.arange(8, dtype=np.int32).view(np.uint8))
        assert not wc.encode_message(msg)
        assert msg.header[CODEC_SLOT] == 0

    def test_transport_filter_is_lossless(self):
        # The filter stage must never quantize: table keys and replies
        # ride the same path as values.
        vals = np.linspace(-3, 3, 4096).astype(np.float32)
        msg = self._msg(vals)
        wc.encode_message(msg)
        wc.decode_message(msg)
        np.testing.assert_array_equal(
            msg.data[0].as_array(np.float32), vals)

    def test_double_encode_is_noop(self):
        msg = self._msg(_power_law_blob(1 << 14, 64, seed=6))
        assert wc.encode_message(msg)
        sizes = [b.size for b in msg.data]
        assert not wc.encode_message(msg)  # already marked
        assert [b.size for b in msg.data] == sizes


class TestNegotiation:
    """Mixed-version handshake: a passthrough peer (no CAP_WIRE_CODEC)
    must keep receiving plain frames. Unit level — the TCP two-process
    flavor lives in test_net_integration.py."""

    def test_controller_collects_and_broadcasts_caps(self):
        from multiverso_tpu.runtime import actor as actors
        from multiverso_tpu.runtime.controller import Controller

        sent = []

        class _FakeZoo:
            net_size = 2
            rank = 0

            def register_actor(self, a):
                pass

            def send_to(self, name, msg):
                sent.append(msg)

        ctrl = Controller(_FakeZoo())
        # Rank 0 advertises the codec (3-int register blob); rank 1 is
        # an old peer sending the legacy 2-int blob.
        new_peer = Message(src=0, dst=0,
                           msg_type=MsgType.Control_Register)
        new_peer.push(Blob(np.array([0, 3, wc.CAP_WIRE_CODEC],
                                    np.int32)))
        old_peer = Message(src=1, dst=0,
                           msg_type=MsgType.Control_Register)
        old_peer.push(Blob(np.array([1, 3], np.int32)))
        ctrl._process_register(new_peer)
        ctrl._process_register(old_peer)
        assert len(sent) == 2
        for reply in sent:
            caps = reply.data[2].as_array(np.int32)
            assert caps[0] == wc.CAP_WIRE_CODEC and caps[1] == 0
        assert actors.CONTROLLER == "controller"  # module really used

    def test_zoo_defaults_unknown_peers_to_passthrough(self):
        from multiverso_tpu.runtime.zoo import Zoo
        zoo = Zoo()
        assert zoo.peer_caps(0) == 0  # before registration: passthrough


class TestBatchAddFraming:
    def test_pack_unpack_identity(self):
        subs = []
        for i in range(5):
            sub = Message(src=2, dst=1, msg_type=MsgType.Request_Add,
                          table_id=i % 2, msg_id=100 + i)
            sub.push(Blob(np.array([i], np.int32).view(np.uint8)))
            sub.push(Blob(np.full(8, float(i), np.float32)))
            if i % 2:
                sub.push(Blob(np.zeros(4, np.uint8)))
            subs.append(sub)
        batch = pack_add_batch(subs)
        assert batch.type == MsgType.Request_BatchAdd
        assert batch.src == 2 and batch.dst == 1
        out = unpack_add_batch(batch)
        assert [(m.table_id, m.msg_id, len(m.data)) for m in out] \
            == [(m.table_id, m.msg_id, len(m.data)) for m in subs]
        for a, b in zip(out, subs):
            for blob_a, blob_b in zip(a.data, b.data):
                np.testing.assert_array_equal(
                    blob_a.as_array(np.uint8), blob_b.as_array(np.uint8))

    def test_truncated_batch_rejected(self):
        sub = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                      table_id=0, msg_id=1)
        sub.push(Blob(np.ones(4, np.float32)))
        batch = pack_add_batch([sub])
        batch.data = batch.data[:-1]  # lose a payload blob
        with pytest.raises(ValueError, match="batch add"):
            unpack_add_batch(batch)

    def test_batch_survives_codec_filter(self):
        # Coalesced messages ride the same filter stage: descriptor and
        # sub-blobs must round-trip through encode/decode.
        subs = []
        for i in range(3):
            sub = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                          table_id=0, msg_id=i)
            sub.push(Blob(np.arange(4, dtype=np.int32).view(np.uint8)))
            sub.push(Blob(_power_law_blob(1 << 13, 50, seed=i)))
            subs.append(sub)
        batch = pack_add_batch(subs)
        wc.encode_message(batch)
        wc.decode_message(batch)
        out = unpack_add_batch(batch)
        assert len(out) == 3
        np.testing.assert_array_equal(
            out[2].data[1].as_array(np.float32),
            _power_law_blob(1 << 13, 50, seed=2))


class TestSparseStreamHelpers:
    """decode_blob_sparse + the public density/break-even helpers the
    sparse collective tier rides (docs/ALLREDUCE.md break-even model)."""

    def test_sparse_frame_streams_without_densifying(self):
        blob = _power_law_blob(1 << 16, 1 << 11, seed=3)
        frame, _ = wc.encode_blob(blob)
        assert wc.peek_tier(frame) in (wc.SPARSE_F32,)
        idx, vals = wc.decode_blob_sparse(frame)
        assert idx is not None
        ref_idx = np.nonzero(blob)[0]
        np.testing.assert_array_equal(np.asarray(idx), ref_idx)
        np.testing.assert_array_equal(np.asarray(vals), blob[ref_idx])
        # scatter-rebuild equals the dense decode
        full = np.zeros(blob.size, np.float32)
        full[idx] = vals
        np.testing.assert_array_equal(full, wc.decode_blob(frame))

    def test_dense_and_raw_frames_stream_as_dense(self):
        dense = np.ones(2000, np.float32)
        frame, _ = wc.encode_blob(dense)
        idx, vals = wc.decode_blob_sparse(frame)
        assert idx is None
        np.testing.assert_array_equal(np.asarray(vals), dense)
        ints = np.arange(100, dtype=np.int64)
        frame, _ = wc.encode_blob(ints)
        idx, vals = wc.decode_blob_sparse(frame)
        assert idx is None and vals.dtype == np.int64
        np.testing.assert_array_equal(np.asarray(vals), ints)

    def test_lossy_sparse_frame_streams(self):
        blob = _power_law_blob(1 << 16, 1 << 11, seed=5)
        frame, residual = wc.encode_blob(blob, lossy=True)
        idx, vals = wc.decode_blob_sparse(frame)
        assert idx is not None
        full = np.zeros(blob.size, np.float32)
        full[idx] = vals
        np.testing.assert_allclose(full + residual, blob, atol=1e-5)

    def test_density_of(self):
        x = np.zeros(1000, np.float32)
        assert wc.density_of(x) == 0.0
        x[:250] = 1.0
        assert wc.density_of(x) == 0.25
        assert wc.density_of(np.zeros(0, np.float32)) == 0.0

    def test_break_even_density_flag_driven(self):
        from multiverso_tpu.util.configure import set_flag
        assert wc.break_even_density() == 0.5
        blob = np.zeros(4096, np.float32)
        blob[: 4096 * 2 // 5] = 1.0  # density 0.4
        assert wc.worth_encoding(blob)
        set_flag("wire_codec_density", 0.3)
        assert wc.break_even_density() == 0.3
        assert not wc.worth_encoding(blob)

    def test_worth_encoding_gates(self):
        # non-f32 and sub-1KB payloads never encode, any density
        assert not wc.worth_encoding(np.zeros(4096, np.float64))
        assert not wc.worth_encoding(np.zeros(64, np.float32))
        assert wc.worth_encoding(np.zeros(4096, np.float32))
