"""Runtime twin of mvlint pass 9 (runtime/thread_roles.py): the role
registry, the ``spawn`` contract, and the ``-debug_locks`` blocking
watchdog — fires on a deliberately-parked DISPATCH thread, stays
silent on a clean PS smoke where every critical thread only idles in
its run loop / mailbox.
"""

import threading
import time

import pytest

import multiverso_tpu as mv
from multiverso_tpu.runtime import thread_roles
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.lock_witness import acquire_timeout


class TestRegistry:
    def test_unknown_role_rejected(self):
        with pytest.raises(ValueError, match="unknown thread role"):
            thread_roles.spawn("TURBO", target=lambda: None)

    def test_registry_entries_are_well_formed(self):
        # The literal table the linter parses: every value a declared
        # role, every key a <rel>::<qualname> entry.
        assert thread_roles.THREAD_ROLES
        for entry, role in thread_roles.THREAD_ROLES.items():
            assert role in thread_roles.ROLES
            assert "::" in entry and entry.endswith(
                tuple("abcdefghijklmnopqrstuvwxyz_"))
        assert set(thread_roles.CRITICAL_ROLES) == {
            thread_roles.DISPATCH, thread_roles.LIVENESS,
            thread_roles.EVENTLOOP}

    def test_spawn_registers_then_unregisters(self):
        release = threading.Event()
        running = threading.Event()

        def body():
            running.set()
            release.wait(timeout=10)

        before = thread_roles.roles_alive().get(
            thread_roles.BACKGROUND, 0)
        thread = thread_roles.spawn(thread_roles.BACKGROUND,
                                    target=body, name="mv-test-bg")
        assert running.wait(timeout=10)
        assert thread_roles.roles_alive().get(
            thread_roles.BACKGROUND, 0) == before + 1
        release.set()
        thread.join(timeout=10)
        assert thread_roles.roles_alive().get(
            thread_roles.BACKGROUND, 0) == before

    def test_spawn_autostarts(self):
        # spawn() starts the thread itself — a second .start() (the
        # old idiom) must be a visible error, not a silent no-op.
        done = threading.Event()
        thread = thread_roles.spawn(thread_roles.BACKGROUND,
                                    target=done.set)
        assert done.wait(timeout=10)
        thread.join(timeout=10)
        with pytest.raises(RuntimeError):
            thread.start()


class TestWatchdog:
    def test_fires_on_parked_dispatch_thread(self):
        set_flag("debug_locks", True)
        set_flag("role_block_budget_ms", 50.0)
        thread_roles.reset_reports()
        gate = threading.Semaphore(0)

        def parked():
            # Deliberately block inside a package frame:
            # acquire_timeout lives in util/lock_witness.py, so the
            # watchdog sees a non-entry, non-mailbox package frame
            # sitting still — the definition of a blocked critical
            # thread.
            with acquire_timeout(gate, 15.0):
                pass

        thread = thread_roles.spawn(thread_roles.DISPATCH,
                                    target=parked,
                                    name="mv-test-parked-dispatch")
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline \
                    and not thread_roles.reports():
                time.sleep(0.02)
            found = thread_roles.reports()
        finally:
            gate.release()
            thread.join(timeout=10)
            thread_roles.reset_reports()
        assert found, "watchdog never fired on a parked DISPATCH"
        assert "DISPATCH" in found[0]
        assert "mv-test-parked-dispatch" in found[0]
        assert "lock_witness" in found[0]  # the stack names the frame

    def test_silent_on_clean_ps_smoke(self):
        # A healthy 2-rank cluster: DISPATCH threads idle in the
        # mailbox (mt_queue) and LIVENESS idles in its own entry
        # frame — neither is "blocked", so no reports.
        set_flag("debug_locks", True)
        set_flag("role_block_budget_ms", 150.0)
        thread_roles.reset_reports()

        def body(rank):
            zoo = mv.current_zoo()
            zoo.barrier()
            return zoo.rank

        try:
            assert LocalCluster(2).run(body) == [0, 1]
            assert thread_roles.reports() == []
        finally:
            thread_roles.reset_reports()
