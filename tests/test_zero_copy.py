"""Zero-copy wire path: golden frames, buffer pool, COW discipline.

PR 15 (docs/MEMORY.md): the send side serializes scatter-gather view
lists drained by vectored ``sendmsg`` writes; the receive side leases
pooled frame buffers and cuts READ-ONLY Blob views out of them. The
contract under test:

* frames are BYTE-IDENTICAL to the legacy flat serializer's across the
  whole header-slot space, codec frames and batch descriptors — no
  wire break, mixed ``-zero_copy`` builds interoperate;
* the pool recycles only export-free buffers (a blob-outlived array can
  never be scribbled), leases always succeed, hit/miss/resident
  accounting holds, and concurrent lease/release survives
  ``-debug_locks``;
* pool-backed views are read-only (mutation raises) and
  ``Blob.materialize()`` is the copy-on-write escape hatch;
* TCP round trips with the pool active deliver correct payloads, both
  directions, including re-sending received (view-backed) blobs.
"""

from __future__ import annotations

import gc
import threading

import numpy as np
import pytest

from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import (CODEC_SLOT, Message, MsgType,
                                         pack_add_batch)
from multiverso_tpu.runtime.tcp import (TcpNet, _deserialize,
                                        _deserialize_frame, _serialize,
                                        serialize_views)
from multiverso_tpu.util import wire_codec as wc
from multiverso_tpu.util.buffer_pool import BufferPool, FrameLease
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.net_util import free_listen_port


def joined(views) -> bytes:
    return b"".join(bytes(v) for v in views)


def random_message(rng: np.random.Generator) -> Message:
    """A message with every header slot 0-9 exercised and a random blob
    mix (dtypes, sizes, empties, raw bytes)."""
    msg = Message(src=int(rng.integers(0, 8)),
                  dst=int(rng.integers(0, 8)),
                  msg_type=MsgType.Request_Get,
                  table_id=int(rng.integers(-1, 16)),
                  msg_id=int(rng.integers(-1, 1 << 20)))
    # Slots 5-9 carry error/codec/version/replica/trace values on real
    # traffic; golden identity must hold for arbitrary ints.
    for slot in range(5, 10):
        msg.header[slot] = int(rng.integers(0, 1 << 30))  # mvlint: ignore[wire-slot]
    dtypes = [np.float32, np.int32, np.uint8, np.float64, np.int64]
    for _ in range(int(rng.integers(0, 4))):
        kind = int(rng.integers(0, 3))
        if kind == 0:
            n = int(rng.integers(0, 300))
            dt = dtypes[int(rng.integers(0, len(dtypes)))]
            msg.push(Blob(rng.standard_normal(n).astype(dt)))
        elif kind == 1:
            msg.push(Blob(bytes(rng.integers(0, 256, int(rng.integers(
                0, 64)), dtype=np.uint8))))
        else:
            msg.push(Blob(np.zeros(0, np.float32)))  # empty blob
    return msg


class TestGoldenFrames:
    def test_property_views_equal_flat_serializer(self):
        rng = np.random.default_rng(123)
        for _ in range(200):
            msg = random_message(rng)
            flat = _serialize(msg)
            views, nbytes = serialize_views(msg)
            assert nbytes == len(flat)
            assert joined(views) == flat

    def test_codec_frames_identical(self):
        # Parted codec blobs (header + stream parts) must frame the
        # same bytes as the flat encode_blob output.
        rng = np.random.default_rng(7)
        dense = rng.standard_normal(4096).astype(np.float32)
        sparse = np.zeros(8192, np.float32)
        idx = np.sort(rng.choice(8192, 200, replace=False))
        sparse[idx] = rng.standard_normal(200).astype(np.float32)
        for payload in (dense, sparse):
            for lossy in (False, True):
                parts, _ = wc.encode_blob_views(payload, lossy=lossy)
                flat, _ = wc.encode_blob(payload, lossy=lossy)
                msg = Message(src=0, dst=1, msg_type=MsgType.Default)
                msg.data.append(Blob.from_parts(parts))
                msg.header[CODEC_SLOT] = 1
                ref = Message(src=0, dst=1, msg_type=MsgType.Default)
                ref.push(Blob(np.frombuffer(flat, np.uint8)))
                ref.header[CODEC_SLOT] = 1
                assert joined(serialize_views(msg)[0]) == _serialize(ref)
                decoded = wc.decode_blob(msg.data[0].data)
                if lossy:
                    np.testing.assert_allclose(decoded, payload,
                                               rtol=0, atol=2e-2)
                else:
                    np.testing.assert_array_equal(decoded, payload)

    def test_encode_message_parts_roundtrip(self):
        sparse = np.zeros(4096, np.float32)
        sparse[::13] = 1.5
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Add)
        msg.push(Blob(sparse))
        assert wc.encode_message(msg)
        assert msg.data[0]._parts is not None  # parted, not joined
        views, _ = serialize_views(msg)
        wc.decode_message(msg)
        np.testing.assert_array_equal(
            msg.data[0].as_array(np.float32), sparse)

    def test_batch_descriptor_frames_identical(self):
        subs = []
        for i in range(3):
            sub = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                          table_id=i, msg_id=100 + i)
            sub.push(Blob(np.arange(4, dtype=np.int32)))
            sub.push(Blob(np.full(8, float(i), np.float32)))
            subs.append(sub)
        batch = pack_add_batch(subs)
        assert joined(serialize_views(batch)[0]) == _serialize(batch)

    def test_view_frame_parses_back(self):
        rng = np.random.default_rng(5)
        for _ in range(40):
            msg = random_message(rng)
            flat = _serialize(msg)
            pool = BufferPool(capacity_mb=4, classes=8)
            lease = pool.lease(len(flat) - 8)
            view = lease.view(len(flat) - 8)
            view[:] = flat[8:]
            out = _deserialize_frame(lease.view(len(flat) - 8), lease)
            ref = _deserialize(bytearray(flat[8:]))
            assert out.header == ref.header == msg.header
            assert len(out.data) == len(msg.data)
            for got, want in zip(out.data, msg.data):
                np.testing.assert_array_equal(got.wire_bytes(),
                                              want.wire_bytes())


class TestBufferPool:
    def test_hit_miss_and_resident_accounting(self):
        pool = BufferPool(capacity_mb=1, classes=4)  # 4K..32K
        lease = pool.lease(5000)  # -> 8K class
        assert lease.nbytes == 8192
        buf_id = id(lease._buf)
        lease.release()
        assert pool.resident_bytes == 8192
        again = pool.lease(6000)
        assert id(again._buf) == buf_id  # recycled, not reallocated
        assert pool.resident_bytes == 0

    def test_release_idempotent(self):
        pool = BufferPool(capacity_mb=1, classes=4)
        lease = pool.lease(100)
        lease.release()
        lease.release()
        assert pool.resident_bytes == 4096

    def test_oversized_frame_unpooled(self):
        pool = BufferPool(capacity_mb=64, classes=3)  # max 16K
        lease = pool.lease(1 << 20)
        assert lease.nbytes == 1 << 20
        lease.release()
        assert pool.resident_bytes == 0  # never retained

    def test_disabled_pool_still_leases(self):
        pool = BufferPool(capacity_mb=0)
        assert not pool.enabled
        lease = pool.lease(4096)
        lease.view(4096)[:] = b"\x07" * 4096
        lease.release()
        assert pool.resident_bytes == 0

    def test_capacity_cap_drops_to_gc(self):
        pool = BufferPool(capacity_mb=1, classes=9)  # max class 1 MB
        a = pool.lease(1 << 20)
        b = pool.lease(1 << 20)
        a.release()
        b.release()
        # Cap is 1 MB: only one buffer retained, the second dropped.
        assert pool.resident_bytes == 1 << 20

    def test_blob_outlives_frame_lease_safety(self):
        """An array extracted from a pool blob and held past the Blob
        must never be aliased by a recycled frame."""
        pool = BufferPool(capacity_mb=4, classes=8)
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get)
        msg.push(Blob(np.arange(1000, dtype=np.float32)))
        flat = _serialize(msg)
        lease = pool.lease(len(flat) - 8)
        lease.view(len(flat) - 8)[:] = flat[8:]
        out = _deserialize_frame(lease.view(len(flat) - 8), lease)
        del lease
        kept = out.data[0].as_array(np.float32)
        del out, msg
        gc.collect()
        # The frame buffer is still exported through `kept`: the pool
        # must NOT have retaken it.
        assert pool.resident_bytes == 0
        # Churn the pool: new leases must not scribble `kept`.
        for _ in range(8):
            lse = pool.lease(len(flat) - 8)
            lse.view(len(flat) - 8)[:] = b"\xff" * (len(flat) - 8)
            lse.release()
        np.testing.assert_array_equal(
            kept, np.arange(1000, dtype=np.float32))
        # Once the last export dies, the parked buffer is reclaimed by
        # a later lease's pending sweep.
        del kept
        gc.collect()
        pool.lease(16).release()
        assert pool.resident_bytes > 0

    def test_frame_recycles_when_blobs_die_first(self):
        pool = BufferPool(capacity_mb=4, classes=8)
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get)
        msg.push(Blob(np.arange(256, dtype=np.float32)))
        flat = _serialize(msg)
        lease = pool.lease(len(flat) - 8)
        lease.view(len(flat) - 8)[:] = flat[8:]
        out = _deserialize_frame(lease.view(len(flat) - 8), lease)
        del lease
        assert pool.resident_bytes == 0  # blob still pins the frame
        del out
        gc.collect()
        assert pool.resident_bytes > 0  # last blob out returned it

    def test_read_only_mutation_guard_raises(self):
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get)
        msg.push(Blob(np.ones(64, np.float32)))
        flat = _serialize(msg)
        pool = BufferPool(capacity_mb=4, classes=8)
        lease = pool.lease(len(flat) - 8)
        lease.view(len(flat) - 8)[:] = flat[8:]
        out = _deserialize_frame(lease.view(len(flat) - 8), lease)
        blob = out.data[0]
        assert blob.pool_backed
        with pytest.raises(ValueError):
            blob.as_array(np.float32)[0] = 2.0
        # Copy-on-write: materialize yields a private writable payload
        # and drops the lease so the frame can recycle.
        blob.materialize()
        assert not blob.pool_backed
        blob.as_array(np.float32)[0] = 2.0
        assert blob.as_array(np.float32)[0] == 2.0

    def test_concurrent_lease_release_under_debug_locks(self):
        set_flag("debug_locks", True)
        try:
            pool = BufferPool(capacity_mb=8, classes=8)
            errors = []

            def pound(seed):
                rng = np.random.default_rng(seed)
                try:
                    for _ in range(300):
                        n = int(rng.integers(1, 200_000))
                        lease = pool.lease(n)
                        view = lease.view(min(n, 64))
                        view[:] = bytes([seed]) * view.nbytes
                        lease.release()
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            threads = [threading.Thread(target=pound, args=(t,))
                       for t in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
            assert not errors, errors
            assert pool.resident_bytes <= 8 << 20
        finally:
            set_flag("debug_locks", False)

    def test_bytearray_blob_is_one_private_copy(self):
        src = bytearray(b"abcdef")
        blob = Blob(src)
        src[0] = ord("z")  # caller keeps mutating its buffer
        assert bytes(blob.as_array(np.uint8)[:1]) == b"a"

    def test_bytes_blob_is_zero_copy_read_only(self):
        blob = Blob(b"abcd")
        arr = blob.as_array(np.uint8)
        assert not arr.flags.writeable
        with pytest.raises(ValueError):
            arr[0] = 1
        blob.materialize().as_array(np.uint8)[0] = 9


class TestTextPayload:
    def test_matches_manual_decode(self):
        msg = Message(src=0, dst=1, msg_type=MsgType.Default)
        text = "héllo wörld — zero copy"
        msg.push(Blob(text.encode()))
        assert msg.text_payload() == text

    def test_index_and_errors(self):
        msg = Message(src=0, dst=1, msg_type=MsgType.Default)
        msg.push(Blob(np.zeros(3, np.float32)))
        msg.push(Blob(b"\xff\xfe not utf8"))
        out = msg.text_payload(1)
        assert "not utf8" in out  # invalid bytes replaced, not raised


class _Pair:
    """Two TcpNet endpoints over loopback."""

    def __enter__(self):
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        self.nets = [TcpNet(r, eps) for r in range(2)]
        return self.nets

    def __exit__(self, *exc):
        for net in self.nets:
            net.finalize()


class TestTcpZeroCopy:
    def test_round_trip_with_pool_active(self):
        with _Pair() as (a, b):
            for i in range(10):
                msg = Message(src=0, dst=1,
                              msg_type=MsgType.Request_Add, msg_id=i)
                msg.push(Blob(np.full(4096, float(i), np.float32)))
                msg.push(Blob(f"payload {i}".encode()))
                a.send(msg)
            for i in range(10):
                got = b.recv(timeout=30)
                assert got.msg_id == i
                assert got.data[0].pool_backed
                np.testing.assert_array_equal(
                    got.data[0].as_array(np.float32),
                    np.full(4096, float(i), np.float32))
                assert got.text_payload(1) == f"payload {i}"

    def test_echo_of_received_view_blobs(self):
        # The pingpong idiom: re-sending a received (pool-view) blob
        # must serialize straight from the leased frame.
        with _Pair() as (a, b):
            msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get,
                          msg_id=3)
            payload = np.linspace(0, 1, 50_000).astype(np.float32)
            msg.push(Blob(payload))
            a.send(msg)
            got = b.recv(timeout=30)
            reply = got.create_reply_message()
            reply.data = list(got.data)
            b.send(reply)
            back = a.recv(timeout=30)
            assert back.type == MsgType.Reply_Get
            np.testing.assert_array_equal(
                back.data[0].as_array(np.float32), payload)

    def test_async_and_large_unpooled_frames(self):
        with _Pair() as (a, b):
            big = np.arange(3 << 20, dtype=np.uint8)  # > max pool class
            msg = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                          msg_id=8)
            msg.push(Blob(big))
            a.send_async(msg)
            a.flush_sends()
            got = b.recv(timeout=30)
            np.testing.assert_array_equal(got.data[0].as_array(np.uint8),
                                          big)

    def test_many_blob_frame_beyond_iov_cap(self):
        # >64 payload views in one frame exercises the sendmsg batching
        # loop (_IOV_CAP) and partial-send advance.
        with _Pair() as (a, b):
            msg = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                          msg_id=9)
            for i in range(200):
                msg.push(Blob(np.full(17, i, np.int32)))
            a.send(msg)
            got = b.recv(timeout=30)
            assert len(got.data) == 200
            for i in (0, 63, 64, 150, 199):
                np.testing.assert_array_equal(
                    got.data[i].as_array(np.int32),
                    np.full(17, i, np.int32))

    def test_legacy_mode_interop(self):
        # -zero_copy=0 endpoints speak the identical wire format: a
        # frame sent by the legacy serializer parses on the view path
        # and vice versa (flags are process-global, so flip between
        # directions).
        with _Pair() as (a, b):
            msg = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                          msg_id=4)
            msg.push(Blob(np.arange(512, dtype=np.float32)))
            set_flag("zero_copy", False)
            try:
                a.send(msg)
                got = b.recv(timeout=30)
            finally:
                set_flag("zero_copy", True)
            np.testing.assert_array_equal(
                got.data[0].as_array(np.float32),
                np.arange(512, dtype=np.float32))
            reply = got.create_reply_message()
            reply.data = list(got.data)
            b.send(reply)  # zero-copy side echoes
            back = a.recv(timeout=30)
            np.testing.assert_array_equal(
                back.data[0].as_array(np.float32),
                np.arange(512, dtype=np.float32))


class TestLeaseViewHelpers:
    def test_lease_view_is_writable_window(self):
        lease = FrameLease(None, bytearray(64))
        view = lease.view(16)
        view[:] = b"x" * 16
        assert lease.nbytes == 64
        lease.release()
        assert lease.nbytes == 0
