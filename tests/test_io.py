"""IO + checkpoint tests: stream roundtrip, text reader, table restore.

Recreates the upstream checkpoint/restore e2e coverage referenced by the
reference's Docker test list (ref: deploy/docker/Dockerfile:105-106) that
was dropped from its snapshot.
"""

import json
import os

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.io import (CheckpointError, StreamFactory, TextReader,
                               load_checkpoint, save_checkpoint,
                               write_bytes_atomic)


@pytest.fixture
def env():
    mv.init([])
    yield
    mv.shutdown()


class TestStream:
    def test_binary_roundtrip(self, tmp_path):
        path = tmp_path / "blob.bin"
        with StreamFactory.get_stream(f"file://{path}", "w") as s:
            s.write(b"hello multiverso")
        with StreamFactory.get_stream(f"file://{path}", "r") as s:
            assert s.read() == b"hello multiverso"

    def test_plain_path_defaults_to_file(self, tmp_path):
        path = str(tmp_path / "plain.bin")
        with StreamFactory.get_stream(path, "w") as s:
            s.write(b"x")
        with StreamFactory.get_stream(path, "r") as s:
            assert s.read() == b"x"

    def test_unknown_scheme_raises(self):
        with pytest.raises(ValueError):
            StreamFactory.get_stream("hdfs://nn/x", "r")

    def test_custom_scheme_registration(self, tmp_path):
        calls = []

        def opener(uri, mode):
            calls.append(uri)
            return StreamFactory.get_stream(str(tmp_path / "alt.bin"), mode)

        StreamFactory.register_scheme("mem", opener)
        try:
            with StreamFactory.get_stream("mem://x", "w") as s:
                s.write(b"y")
            assert calls == ["mem://x"]
        finally:
            StreamFactory._openers.pop("mem", None)


class TestTextReader:
    def test_get_line(self, tmp_path):
        path = tmp_path / "text.txt"
        path.write_text("alpha\nbeta\r\ngamma")
        reader = TextReader(str(path))
        assert reader.get_line() == "alpha"
        assert reader.get_line() == "beta"
        assert reader.get_line() == "gamma"
        assert reader.get_line() is None
        reader.close()

    def test_long_lines_cross_buffer(self, tmp_path):
        path = tmp_path / "long.txt"
        line = "z" * 5000
        path.write_text(f"{line}\nshort")
        reader = TextReader(str(path), buf_size=64)
        assert reader.get_line() == line
        assert reader.get_line() == "short"


class TestCheckpoint:
    def test_array_matrix_kv_roundtrip(self, env, tmp_path):
        prefix = str(tmp_path / "ckpt")
        arr = mv.create_array_table(50)
        mat = mv.create_matrix_table(12, 4)
        kv = mv.create_kv_table()
        arr.add(np.arange(50, dtype=np.float32))
        mat.add_rows(np.array([3], np.int32), np.ones((1, 4), np.float32))
        kv.add([9], [4.5])
        assert save_checkpoint(prefix) == 3

        # Wipe by negating (the reference LogReg uploads loaded models with
        # a negate-add trick, ref: ps_model.cpp:116-169 — here we just
        # overwrite and restore).
        arr.add(-2 * np.arange(50, dtype=np.float32))
        assert load_checkpoint(prefix) == 3
        np.testing.assert_array_equal(arr.get(),
                                      np.arange(50, dtype=np.float32))
        np.testing.assert_array_equal(mat.get_rows(np.array([3], np.int32)),
                                      np.ones((1, 4), np.float32))
        assert kv.get([9])[9] == pytest.approx(4.5)

    def test_atomic_write_leaves_no_temp_debris(self, tmp_path):
        path = tmp_path / "nested" / "obj.bin"
        write_bytes_atomic(str(path), b"payload", fsync=True)
        assert path.read_bytes() == b"payload"
        assert [p.name for p in path.parent.iterdir()] == ["obj.bin"]

    def test_torn_table_file_rejected_before_any_restore(self, env,
                                                         tmp_path):
        """A truncated table payload (crash mid-write, pre-rename copy
        of an older era, disk corruption) must fail load_checkpoint
        LOUDLY before any table is touched — not restore garbage."""
        prefix = str(tmp_path / "ckpt")
        arr = mv.create_array_table(32)
        arr.add(np.arange(32, dtype=np.float32))
        assert save_checkpoint(prefix) == 1
        table_file = tmp_path / "ckpt.table0.rank0"
        table_file.write_bytes(table_file.read_bytes()[:-4])
        arr.add(np.ones(32, np.float32))  # post-save state to preserve
        with pytest.raises(CheckpointError, match="torn"):
            load_checkpoint(prefix)
        # Nothing was restored: the live table still has the later add.
        assert arr.get()[1] == pytest.approx(2.0)

    def test_torn_manifest_rejected(self, env, tmp_path):
        prefix = str(tmp_path / "ckpt")
        mv.create_array_table(8).add(np.ones(8, np.float32))
        assert save_checkpoint(prefix) == 1
        manifest = tmp_path / "ckpt.manifest.rank0.json"
        manifest.write_bytes(manifest.read_bytes()[:-10])
        with pytest.raises(CheckpointError, match="torn"):
            load_checkpoint(prefix)

    def test_partial_manifest_table_count_mismatch_rejected(self, env,
                                                            tmp_path):
        """A manifest covering fewer tables than the rank registered
        (partial save, table-creation drift between save and load) must
        refuse the mixed restore."""
        prefix = str(tmp_path / "ckpt")
        mv.create_array_table(8).add(np.ones(8, np.float32))
        assert save_checkpoint(prefix) == 1
        mv.create_kv_table()  # registered after the save
        with pytest.raises(CheckpointError, match="covers 1 tables"):
            load_checkpoint(prefix)

    def test_incomplete_flag_rejected(self, env, tmp_path):
        prefix = str(tmp_path / "ckpt")
        mv.create_array_table(8)
        assert save_checkpoint(prefix) == 1
        manifest = tmp_path / "ckpt.manifest.rank0.json"
        doc = json.loads(manifest.read_text())
        doc["complete"] = False
        manifest.write_text(json.dumps(doc))
        with pytest.raises(CheckpointError, match="partial"):
            load_checkpoint(prefix)

    def test_legacy_checkpoint_without_manifest_still_loads(self, env,
                                                            tmp_path):
        """Pre-manifest checkpoints (just the table files) keep loading
        through the legacy path."""
        prefix = str(tmp_path / "ckpt")
        arr = mv.create_array_table(16)
        arr.add(np.full(16, 3.0, np.float32))
        assert save_checkpoint(prefix) == 1
        os.unlink(tmp_path / "ckpt.manifest.rank0.json")
        arr.add(np.ones(16, np.float32))
        assert load_checkpoint(prefix) == 1
        assert arr.get()[0] == pytest.approx(3.0)


class TestHttpStream:
    """The second StreamFactory scheme (the reference's hdfs:// role,
    ref: io.cpp:8-21, hdfs_stream.h:10-60): a real HTTP object endpoint
    served in-process."""

    @pytest.fixture
    def http_store(self):
        import http.server
        import threading

        store = {}

        class Handler(http.server.BaseHTTPRequestHandler):
            def _authorized(self):
                # When the store holds a "__require_auth__" sentinel,
                # demand that exact Authorization header.
                needed = store.get("__require_auth__")
                if needed is None:
                    return True
                if self.headers.get("Authorization") == needed.decode():
                    return True
                self.send_response(401)
                self.end_headers()
                return False

            def do_PUT(self):
                if not self._authorized():
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                fail = store.get("__fail_put__")
                if fail is not None:  # transport-failure injection
                    self.send_response(int(fail))
                    self.end_headers()
                    return
                store[self.path] = body
                self.send_response(201)
                self.end_headers()

            def do_GET(self):
                if not self._authorized():
                    return
                body = store.get(self.path)
                if body is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        yield f"http://127.0.0.1:{server.server_address[1]}", store
        server.shutdown()

    def test_binary_roundtrip(self, http_store):
        import multiverso_tpu.io.http_stream  # noqa: F401 - registers scheme
        base, store = http_store
        payload = bytes(range(256)) * 100
        with StreamFactory.get_stream(f"{base}/obj/blob.bin", "w") as s:
            s.write(payload[:1000])
            s.write(payload[1000:])
        assert store["/obj/blob.bin"] == payload
        with StreamFactory.get_stream(f"{base}/obj/blob.bin", "r") as s:
            assert s.read() == payload

    def test_put_failure_surfaces_ioerror_naming_uri_and_status(
            self, http_store):
        """The whole buffered object rides close()'s one PUT: a
        rejected PUT must surface as an IOError naming the uri and
        the HTTP status — not vanish (the caller thinks the object
        was stored) and not read as a generic urllib message that
        names neither."""
        import multiverso_tpu.io.http_stream  # noqa: F401 - registers scheme
        base, store = http_store
        store["__fail_put__"] = 507  # Insufficient Storage
        uri = f"{base}/obj/lost.bin"
        stream = StreamFactory.get_stream(uri, "w")
        stream.write(b"precious bytes")
        with pytest.raises(IOError) as exc:
            stream.close()
        assert uri in str(exc.value)
        assert "507" in str(exc.value)
        assert "/obj/lost.bin" not in store  # nothing silently stored
        assert not stream.good()   # the stream IS closed
        stream.close()             # idempotent: no second PUT attempt
        del store["__fail_put__"]

    def test_auth_headers_attached(self, http_store):
        # The hdfs role was an AUTHENTICATED store
        # (ref: hdfs_stream.h:10-60): a server demanding credentials
        # must reject bare requests and accept set_auth'd ones, for
        # both static dicts and per-uri callables.
        from multiverso_tpu.io import http_stream
        base, store = http_store
        store["/secret.bin"] = b"classified"
        store["__require_auth__"] = b"Bearer tok123"
        try:
            with pytest.raises(Exception):
                with StreamFactory.get_stream(f"{base}/secret.bin",
                                              "r") as s:
                    s.read()
            http_stream.set_auth({"Authorization": "Bearer tok123"})
            with StreamFactory.get_stream(f"{base}/secret.bin", "r") as s:
                assert s.read() == b"classified"
            http_stream.set_auth(
                lambda uri: {"Authorization": "Bearer tok123"})
            with StreamFactory.get_stream(f"{base}/auth_put.bin",
                                          "w") as s:
                s.write(b"payload")
            assert store["/auth_put.bin"] == b"payload"
        finally:
            http_stream.set_auth(None)

    def test_env_token_default(self, http_store, monkeypatch):
        from multiverso_tpu.io import http_stream
        base, store = http_store
        store["/tok.bin"] = b"x"
        store["__require_auth__"] = b"Bearer envtok"
        monkeypatch.setenv("MV_HTTP_AUTH_TOKEN", "envtok")
        # Bare token must NOT ride plain http to an unnamed host...
        with pytest.raises(Exception):
            with StreamFactory.get_stream(f"{base}/tok.bin", "r") as s:
                s.read()
        # ...but is attached once the host is explicitly scoped.
        monkeypatch.setenv("MV_HTTP_AUTH_HOST", "127.0.0.1")
        with StreamFactory.get_stream(f"{base}/tok.bin", "r") as s:
            assert s.read() == b"x"

    def test_redirect_strips_auth_cross_host(self):
        # urllib forwards Authorization across redirects by default; the
        # scoped handler must strip it when the redirect leaves the
        # original host (and keep it same-host).
        import io
        import urllib.request
        from email.message import Message as HdrMessage
        from multiverso_tpu.io.http_stream import _AuthScopedRedirectHandler

        def redirect(newurl):
            req = urllib.request.Request("https://a.example/obj")
            req.add_header("Authorization", "Bearer tok")
            hdrs = HdrMessage()
            hdrs["Location"] = newurl
            fp = io.BytesIO(b"")
            return _AuthScopedRedirectHandler().redirect_request(
                req, fp, 302, "Found", hdrs, newurl)

        kept = redirect("https://a.example/elsewhere")
        assert kept.headers.get("Authorization") == "Bearer tok"
        stripped = redirect("https://evil.example/steal")
        assert "Authorization" not in stripped.headers
        # Same host but scheme downgrade / other port = different origin.
        downgraded = redirect("http://a.example/obj")
        assert "Authorization" not in downgraded.headers
        other_port = redirect("https://a.example:8443/obj")
        assert "Authorization" not in other_port.headers

    def test_text_reader_over_http(self, http_store):
        import multiverso_tpu.io.http_stream  # noqa: F401
        base, store = http_store
        store["/corpus.txt"] = b"alpha beta\ngamma\n"
        reader = TextReader(f"{base}/corpus.txt")
        assert reader.get_line() == "alpha beta"
        assert reader.get_line() == "gamma"
        assert reader.get_line() is None
        reader.close()

    def test_checkpoint_over_http(self, env, http_store):
        import multiverso_tpu.io.http_stream  # noqa: F401
        base, _ = http_store
        table = mv.create_array_table(16)
        table.add(np.arange(16, dtype=np.float32))
        assert save_checkpoint(f"{base}/ckpt") == 1
        table.add(np.ones(16, np.float32))
        assert load_checkpoint(f"{base}/ckpt") == 1
        np.testing.assert_array_equal(table.get(),
                                      np.arange(16, dtype=np.float32))
