"""Event-loop transport core: per-transition state-machine tests.

The transport runs one selector loop per rank (docs/THREADS.md
EVENTLOOP): every accept, nonblocking connect, frame read/write, retry
and pacing timer multiplexes onto it, and each outbound peer is a
state machine CONNECTING → HANDSHAKE → READY → DRAINING → DEAD. These
tests drive every transition over real loopback sockets and pin the
invariants the refactor exists for: O(1) transport threads in peer
count, no thread parked toward a corpse, nonblocking connect backoff,
and a goodbye-draining finalize that survives a peer dying mid-drain.

The suite-level teardown leak guard (conftest.py) asserts around every
test here that role-thread and fd counts return to baseline.
"""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.core.message import Blob, Message, MsgType
from multiverso_tpu.runtime import thread_roles
from multiverso_tpu.runtime.net import PeerLostError
from multiverso_tpu.runtime.tcp import TcpNet
from multiverso_tpu.util.configure import get_flag, set_flag
from multiverso_tpu.util.dashboard import Dashboard
from multiverso_tpu.util.net_util import free_listen_port


def cnt(name):
    return Dashboard.get(name).count


def data_msg(src, dst, msg_id=0, words=64):
    msg = Message(src=src, dst=dst, msg_type=MsgType.Request_Add,
                  msg_id=msg_id)
    msg.push(Blob(np.full(words, float(msg_id), np.float32)))
    return msg


def peer_state(net, dst):
    """Read a peer machine's state on the loop thread (states are
    loop-confined; run_sync is the sanctioned introspection port)."""
    out = []

    def probe():
        peer = net._out_peers.get(dst)
        out.append(None if peer is None else peer.state)

    assert net._loop.run_sync(probe), "loop did not run the probe"
    return out[0]


def wait_for(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


class _Pair:
    def __enter__(self):
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        self.nets = [TcpNet(r, eps) for r in range(2)]
        return self.nets

    def __exit__(self, *exc):
        for net in self.nets:
            net.finalize()


# ---------------------------------------------------------------------------
# CONNECTING → HANDSHAKE → READY
# ---------------------------------------------------------------------------

def test_connect_reaches_ready_and_transitions_count():
    before = {s: cnt(f"NET_PEER_STATE[{s}]")
              for s in ("CONNECTING", "HANDSHAKE", "READY")}
    with _Pair() as (a, b):
        a.send(data_msg(0, 1, msg_id=1))
        got = b.recv(timeout=10)
        assert got.msg_id == 1
        assert peer_state(a, 1) == "READY"
        for s in ("CONNECTING", "HANDSHAKE", "READY"):
            assert cnt(f"NET_PEER_STATE[{s}]") > before[s], s


def test_transport_threads_are_o1_in_peers():
    """One EVENTLOOP thread per rank regardless of peer count: the
    thread-per-peer writer model and per-conn reader threads are gone."""
    n = 4
    eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(n)]
    nets = [TcpNet(r, eps) for r in range(n)]
    try:
        loops_before = thread_roles.roles_alive().get(
            thread_roles.EVENTLOOP, 0)
        assert loops_before >= n
        # Full mesh: rank 0 talks to every peer, everyone answers.
        for dst in range(1, n):
            nets[0].send(data_msg(0, dst, msg_id=dst))
        for dst in range(1, n):
            got = nets[dst].recv(timeout=10)
            nets[dst].send(data_msg(dst, 0, msg_id=got.msg_id))
        for _ in range(1, n):
            assert nets[0].recv(timeout=10) is not None
        alive = thread_roles.roles_alive()
        # Still exactly one loop per endpoint — connections added no
        # threads (no WRITER on pure TCP, no reader/acceptor roles).
        assert alive.get(thread_roles.EVENTLOOP, 0) == loops_before
        assert alive.get(thread_roles.WRITER, 0) == 0
    finally:
        for net in nets:
            net.finalize()


# ---------------------------------------------------------------------------
# Nonblocking connect backoff (satellite 1)
# ---------------------------------------------------------------------------

def test_connect_backoff_retries_until_listener_appears():
    """Frames queued while the peer's port is still closed survive
    ECONNREFUSED dials: the loop retries on a backoff timer (no thread
    parks) and delivery completes once the listener binds."""
    eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
    a = TcpNet(0, eps)
    b = None
    try:
        a.send_async(data_msg(0, 1, msg_id=9))
        # Let several dial attempts fail before the listener exists.
        time.sleep(0.3)
        assert peer_state(a, 1) in ("CONNECTING", "HANDSHAKE")
        b = TcpNet(1, eps)
        a.flush_sends(1, timeout=10.0)
        got = b.recv(timeout=10)
        assert got.msg_id == 9
        assert peer_state(a, 1) == "READY"
    finally:
        a.finalize()
        if b is not None:
            b.finalize()


def test_connect_deadline_kills_peer_with_typed_error():
    saved = get_flag("connect_timeout_s")
    set_flag("connect_timeout_s", 0.4)
    dead_before = cnt("NET_PEER_STATE[DEAD]")
    eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
    a = TcpNet(0, eps)
    try:
        a.send_async(data_msg(0, 1))
        with pytest.raises(PeerLostError, match="rank 1"):
            a.flush_sends(1, timeout=10.0)
        assert cnt("NET_PEER_STATE[DEAD]") > dead_before
        assert a.queue_depths().get(1, 0) == 0
    finally:
        a.finalize()
        set_flag("connect_timeout_s", saved)


# ---------------------------------------------------------------------------
# READY → DEAD and reconnect
# ---------------------------------------------------------------------------

def test_drop_connection_then_resend_reconnects():
    with _Pair() as (a, b):
        a.send(data_msg(0, 1, msg_id=1))
        assert b.recv(timeout=10).msg_id == 1
        a.drop_connection(1)
        wait_for(lambda: peer_state(a, 1) is None, what="peer retired")
        # The next send dials a fresh machine transparently.
        a.send(data_msg(0, 1, msg_id=2))
        assert b.recv(timeout=10).msg_id == 2
        assert peer_state(a, 1) == "READY"


def test_idle_remote_eof_retires_quietly_then_reconnects():
    """The loop registers outbound sockets for READ as an EOF probe.
    A remote teardown while our queue is idle must NOT report
    peer-lost (nothing was lost) — just retire the machine so the next
    send dials fresh. The rejoin shape: the peer comes back on the
    same endpoint and traffic resumes."""
    reports = []
    eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
    a, b = TcpNet(0, eps), TcpNet(1, eps)
    b2 = None
    try:
        a.on_peer_lost = lambda dst, exc: reports.append((dst, exc))
        a.send(data_msg(0, 1, msg_id=1))
        assert b.recv(timeout=10).msg_id == 1
        b.finalize()  # remote end closes the established link
        wait_for(lambda: peer_state(a, 1) is None,
                 what="idle EOF quiet retire")
        assert reports == []
        b2 = TcpNet(1, eps)  # rank 1 rejoins on the same endpoint
        a.send(data_msg(0, 1, msg_id=2))
        assert b2.recv(timeout=10).msg_id == 2
    finally:
        a.finalize()
        if b2 is not None:
            b2.finalize()


# ---------------------------------------------------------------------------
# DRAINING: goodbye drain, post-finalize submit, mid-drain death
# ---------------------------------------------------------------------------

def test_finalize_drains_queued_frames_then_goodbye():
    eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
    a, b = TcpNet(0, eps), TcpNet(1, eps)
    try:
        for i in range(32):
            a.send_async(data_msg(0, 1, msg_id=i, words=4096))
        a.finalize()  # DRAINING: queued frames flush, then goodbye
        for i in range(32):
            assert b.recv(timeout=10).msg_id == i
        with pytest.raises(RuntimeError, match="finalized"):
            a.send_async(data_msg(0, 1))
    finally:
        b.finalize()


def test_peer_death_mid_draining_does_not_hang_finalize():
    """A peer dying while its queue drains goodbye-ward must fail the
    drain over to DEAD, not park finalize: the bounded flush eats the
    PeerLostError and teardown completes."""
    eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
    a, b = TcpNet(0, eps), TcpNet(1, eps)
    finalized = threading.Event()
    try:
        # Establish, then queue far more than the kernel socket buffer
        # while b never drains its inbox — a's frames sit queued.
        a.send(data_msg(0, 1, msg_id=0))
        assert b.recv(timeout=10).msg_id == 0
        for i in range(24):
            a.send_async(data_msg(0, 1, msg_id=i, words=262144))  # 1 MB

        def run_finalize():
            a.finalize()
            finalized.set()

        t = threading.Thread(target=run_finalize)
        t.start()
        # Kill the remote end mid-drain; a's flush must wake on the
        # dirty close instead of waiting out the full drain budget.
        time.sleep(0.2)
        b.finalize()
        assert finalized.wait(timeout=30), "finalize hung on dead peer"
        t.join(5)
    finally:
        if not finalized.is_set():
            a.finalize()
