"""The bench harness's loss-proof properties (VERDICT r4 #1).

Round 4's entire performance story was erased by a driver timeout
because bench.py printed its one JSON line only at the very end. These
tests pin the defenses: cumulative emission after every merge, the
wall-budget skip, failure isolation, and the baseline cache's
source-sensitivity. They run bench.py's HARNESS only — no corpus, no
device work (bench imports jax lazily inside phase functions)."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def _last_json(capsys):
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip()]
    assert lines, "nothing emitted"
    return json.loads(lines[-1])


class TestResultEmission:
    def test_every_merge_emits_a_complete_line(self, capsys):
        r = bench._Result()
        r.merge(alpha=1)
        r.merge(beta={"x": [1, 2]})
        doc = _last_json(capsys)
        assert doc["detail"]["alpha"] == 1
        assert doc["detail"]["beta"] == {"x": [1, 2]}
        assert "elapsed_sec" in doc["detail"]["wall_budget"]

    def test_phase_failure_is_recorded_and_does_not_propagate(
            self, capsys):
        r = bench._Result()

        def boom():
            raise RuntimeError("phase exploded")

        out = r.run("exploding_phase", boom)
        assert out is None
        doc = _last_json(capsys)
        assert "phase exploded" in doc["detail"]["exploding_phase_error"]

    def test_wall_budget_skips_instead_of_starting(self, capsys,
                                                   monkeypatch):
        monkeypatch.setattr(bench, "WALL_BUDGET_SEC", 1.0)
        monkeypatch.setattr(bench, "_BENCH_T0", time.monotonic() - 10)
        r = bench._Result()
        ran = []
        out = r.run("local_train", lambda: ran.append(1))
        assert out is None and not ran
        doc = _last_json(capsys)
        assert "local_train" in doc["detail"]["wall_budget"]["skipped"]

    def test_est_override_admits_cheap_cached_phase(self, capsys,
                                                    monkeypatch):
        # A cached baseline costs seconds; the skip check must honor
        # the caller's estimate override instead of the worst case.
        monkeypatch.setattr(bench, "WALL_BUDGET_SEC", 60.0)
        monkeypatch.setattr(bench, "_BENCH_T0", time.monotonic() - 45)
        r = bench._Result()
        assert r.run("cpu_baseline", lambda: "hit", est=10) == "hit"
        assert r.run("cpu_baseline_2", lambda: "never") is None

    def test_sigterm_handler_emits_interrupted_record(self, capsys,
                                                      monkeypatch):
        # Drive the real kill handler (os._exit neutered): it must
        # print a complete line carrying the interrupt marker.
        import signal
        exits = []
        monkeypatch.setattr(os, "_exit", exits.append)
        r = bench._Result()
        r.merge(gamma=3)
        saved_term = signal.getsignal(signal.SIGTERM)
        saved_int = signal.getsignal(signal.SIGINT)
        try:
            bench._install_kill_emitter(r)
            handler = signal.getsignal(signal.SIGTERM)
            capsys.readouterr()
            handler(signal.SIGTERM, None)
        finally:
            signal.signal(signal.SIGTERM, saved_term)
            signal.signal(signal.SIGINT, saved_int)
        doc = _last_json(capsys)
        assert doc["detail"]["gamma"] == 3
        assert doc["detail"]["wall_budget"]["interrupted"] == "SIGTERM"
        assert exits == [98]

    def test_sigterm_handler_falls_back_to_last_serialized_line(
            self, capsys, monkeypatch):
        # If a fresh serialization fails (mid-merge dict mutation),
        # the handler must reprint the LAST complete emitted line
        # rather than die with nothing on stdout.
        import signal
        monkeypatch.setattr(os, "_exit", lambda code: None)
        r = bench._Result()
        r.merge(delta=4)
        monkeypatch.setattr(
            r, "emit",
            lambda: (_ for _ in ()).throw(RuntimeError("torn")))
        saved_term = signal.getsignal(signal.SIGTERM)
        saved_int = signal.getsignal(signal.SIGINT)
        try:
            bench._install_kill_emitter(r)
            handler = signal.getsignal(signal.SIGTERM)
            capsys.readouterr()
            handler(signal.SIGTERM, None)
        finally:
            signal.signal(signal.SIGTERM, saved_term)
            signal.signal(signal.SIGINT, saved_int)
        doc = _last_json(capsys)
        assert doc["detail"]["delta"] == 4  # the pre-serialized line


class TestBaselineCache:
    def test_key_tracks_source_files(self, tmp_path):
        src = tmp_path / "dep.py"
        src.write_text("A = 1\n")
        p1 = bench._baseline_cache_path("cpu_baseline", [str(src)])
        src.write_text("A = 2\n")
        p2 = bench._baseline_cache_path("cpu_baseline", [str(src)])
        assert p1 != p2  # edited dependency invalidates
        src.write_text("A = 1\n")
        assert bench._baseline_cache_path(
            "cpu_baseline", [str(src)]) == p1  # content-addressed

    def test_roundtrip_and_cached_marker(self, tmp_path, monkeypatch,
                                         capsys):
        # Point the cache dir at tmp by relocating bench's notion of
        # its own file.
        monkeypatch.setattr(bench, "__file__",
                            str(tmp_path / "bench.py"))
        src = tmp_path / "dep.py"
        src.write_text("A = 1\n")
        calls = []

        def fake_baseline():
            calls.append(1)
            return {"wps": 123.0, "epoch_losses": [1.0]}

        out1 = bench._cached_baseline("cpu_baseline", [str(src)],
                                      fake_baseline)
        out2 = bench._cached_baseline("cpu_baseline", [str(src)],
                                      fake_baseline)
        assert len(calls) == 1  # second call served from disk
        assert "cached" not in out1 and out2["cached"] is True
        assert out2["wps"] == 123.0
        est = bench._baseline_est("cpu_baseline", [str(src)])
        assert est == 10  # cache hit -> seconds, not the worst case
        src.write_text("A = 2\n")
        assert bench._baseline_est(
            "cpu_baseline", [str(src)]) == bench._PHASE_EST[
                "cpu_baseline"]


class TestFlagGuard:
    def test_restores_values_set_inside(self):
        from multiverso_tpu.util.configure import get_flag, set_flag
        before = get_flag("max_get_staleness")
        with bench.flag_guard():
            set_flag("max_get_staleness", 42)
            set_flag("trace_sample_rate", 0.5)
            assert get_flag("max_get_staleness") == 42
        assert get_flag("max_get_staleness") == before
        assert get_flag("trace_sample_rate") == 0.0

    def test_restores_on_exception(self):
        from multiverso_tpu.util.configure import get_flag, set_flag

        @bench.flag_guarded
        def phase():
            set_flag("net_pace_mbps", 99.0)
            raise RuntimeError("mid-phase failure")

        try:
            phase()
        except RuntimeError:
            pass
        assert get_flag("net_pace_mbps") == 0.0

    def test_implicit_registration_restores_canonical_default(self):
        # A tunable applied (e.g. via Control_Config) before its
        # defining module imported is implicitly registered with
        # default == the applied value; the guard must restore the
        # CANONICAL default, not that accidental one — or the tuned
        # knob would leak into every later phase's default numbers.
        from multiverso_tpu.util.configure import (CANONICAL_FLAGS,
                                                   FlagRegister,
                                                   get_flag, set_flag)
        reg = FlagRegister.get()
        name = "serving_batch_window_ms"
        saved = reg._flags.pop(name, None)
        try:
            with bench.flag_guard():
                set_flag(name, 9.5)  # implicit registration
                assert get_flag(name) == 9.5
            assert get_flag(name) == CANONICAL_FLAGS[name]
        finally:
            if saved is not None:
                reg._flags[name] = saved
