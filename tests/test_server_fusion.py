"""Server-side request fusion tests (ISSUE 19, docs/SERVER_ENGINE.md).

Three layers:

* unit tests for ``MtQueue.pop_batch`` (bounded atomic drain: item/byte
  caps, the one-message fallback, watermark and depth-sampling
  interaction) and the pure planner in ``runtime/fusion.py``
  (classification, barriers, per-table op exclusivity, BatchAdd
  all-or-nothing);
* server-level dispatch tests driving ``Server._dispatch_fused``
  directly against stub tables: fused group shapes, arrival-order reply
  emission around barriers (including a shard-migration message
  mid-batch), post-batch version stamping (monotone + RYW-safe),
  per-entry error isolation, the ``PartialFuseError`` replay-the-tail
  accounting, and the SyncServer force-disable;
* integration: the same workload against fusion-off (``-server_fuse_max
  =1``) and fusion-on clusters must produce bit-identical Gets and
  exact sums across Matrix (dense + sparse), Array and KV tables —
  integer-valued float32 deltas keep every fold order exact — plus a
  chaos smoke (reordered/delayed data frames) with zero wrong reads.
"""

import threading

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import (Message, MsgType,
                                         pack_add_batch, reply_version,
                                         take_error)
from multiverso_tpu.runtime import actor as actors
from multiverso_tpu.runtime import fusion
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.runtime.server import Server, SyncServer
from multiverso_tpu.tables.table_interface import ServerTable
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.mt_queue import MtQueue


# ---------------------------------------------------------------------------
# unit: MtQueue.pop_batch
# ---------------------------------------------------------------------------

class TestPopBatch:
    def test_drains_in_order_up_to_item_cap(self):
        q = MtQueue()
        for i in range(10):
            q.push(i)
        assert q.pop_batch(4) == [0, 1, 2, 3]
        assert q.pop_batch(100) == [4, 5, 6, 7, 8, 9]

    def test_byte_budget_bounds_the_tail(self):
        q = MtQueue()
        for v in (100, 1, 1, 50):
            q.push(v)
        # 100 pops unconditionally, then 1 + 1 fit the remaining
        # budget; 50 does not and stays queued.
        assert q.pop_batch(10, max_bytes=102,
                           size_of=lambda v: v) == [100, 1, 1]
        assert q.pop_batch(10, max_bytes=102, size_of=lambda v: v) == [50]

    def test_oversized_first_item_pops_alone(self):
        q = MtQueue()
        q.push(500)
        q.push(1)
        # The one-message fallback: a request larger than the whole
        # byte cap still pops (alone), or the mailbox would wedge.
        assert q.pop_batch(10, max_bytes=10, size_of=lambda v: v) == [500]
        assert q.pop_batch(10, max_bytes=10, size_of=lambda v: v) == [1]

    def test_timeout_on_empty_returns_empty(self):
        q = MtQueue()
        assert q.pop_batch(4, timeout=0.01) == []

    def test_exit_drains_remainder_then_returns_empty(self):
        q = MtQueue()
        q.push("a")
        q.push("b")
        q.exit()
        assert q.pop_batch(8) == ["a", "b"]
        assert q.pop_batch(8) == []

    def test_blocked_pop_batch_wakes_on_push(self):
        q = MtQueue()
        got = []

        def consume():
            got.extend(q.pop_batch(4, timeout=5.0))

        t = threading.Thread(target=consume)
        t.start()
        q.push(7)
        t.join(timeout=5.0)
        assert not t.is_alive() and got == [7]

    def test_watermark_survives_a_batch_drain(self):
        # The depth high watermark is a push-side observable
        # (docs/OBSERVABILITY.md MAILBOX_DEPTH): draining five at once
        # must read exactly like five serial pops did.
        q = MtQueue()
        for i in range(5):
            q.push(i)
        assert q.depth_high_watermark == 5
        q.pop_batch(5)
        assert q.depth_high_watermark == 5
        q.reset_depth_watermark()
        assert q.depth_high_watermark == 0

    def test_depth_sampling_stays_push_side(self):
        # track_depth appends one reservoir sample per PUSH; a batched
        # drain must not add pop-side samples (the reservoir would
        # double-count under fusion).
        from multiverso_tpu.util.dashboard import samples
        name = "MAILBOX_DEPTH[fusion-test]"
        q = MtQueue()
        q.track_depth(name)
        before = samples(name).snapshot()["count"]
        for i in range(6):
            q.push(i)
        q.pop_batch(6)
        assert samples(name).snapshot()["count"] - before == 6

    # -- racing interleavings (real threads; the systematic-schedule
    #    twin of each lives in tools/mvchk specs) ---------------------

    def test_producer_races_greedy_drain_at_byte_cap(self):
        # A producer streams sized items while the consumer drains in
        # byte-capped batches. Whatever the interleaving: nothing is
        # lost or duplicated, concatenated batches are the push order
        # (single producer => global FIFO), and every batch TAIL
        # respects the cap (the first item is the one-message
        # fallback and may exceed it).
        sizes = [7, 120, 3, 40, 40, 40, 9, 200, 1, 1, 1, 55] * 25
        cap = 100
        q = MtQueue()
        batches = []

        def consume():
            taken = 0
            while taken < len(sizes):
                batch = q.pop_batch(8, max_bytes=cap,
                                    size_of=lambda v: v, timeout=5.0)
                assert batch, "drain starved with items outstanding"
                batches.append(batch)
                taken += len(batch)

        t = threading.Thread(target=consume)
        t.start()
        for v in sizes:
            q.push(v)
        t.join(timeout=30.0)
        assert not t.is_alive()
        flat = [v for b in batches for v in b]
        assert flat == sizes
        for batch in batches:
            assert sum(batch[1:]) <= cap - batch[0] or len(batch) == 1

    def test_exit_races_block_for_first(self):
        # stop() (queue exit) racing the block-for-first wait: the
        # parked pop_batch must always wake and return [] — a lost
        # exit wakeup here is exactly the mvchk `mtqueue-exit-wakes`
        # deadlock, reproduced on real threads across many races.
        for _ in range(50):
            q = MtQueue()
            got = []
            started = threading.Event()

            def consume():
                started.set()
                got.append(q.pop_batch(4, timeout=5.0))

            t = threading.Thread(target=consume)
            t.start()
            started.wait(timeout=5.0)
            q.exit()
            t.join(timeout=5.0)
            assert not t.is_alive(), "pop_batch missed the exit wakeup"
            assert got == [[]]

    def test_exit_races_drain_never_hides_items(self):
        # Exit-drain ordering under a live race: everything pushed
        # BEFORE exit() must come out of post-exit drains, in order,
        # before the terminal [] — exit is a close, not a discard.
        for _ in range(25):
            q = MtQueue()
            items = list(range(40))
            recovered = []

            def consume():
                while True:
                    batch = q.pop_batch(7)
                    if not batch:
                        return
                    recovered.extend(batch)

            t = threading.Thread(target=consume)
            t.start()
            for v in items:
                q.push(v)
            q.exit()
            t.join(timeout=10.0)
            assert not t.is_alive()
            # The consumer may legitimately observe [] the instant
            # exit lands only AFTER the buffer is empty.
            assert recovered == items


# ---------------------------------------------------------------------------
# server-level: stub zoo/tables driving the real dispatch machinery
# ---------------------------------------------------------------------------

class _StubZoo:
    """The minimum surface Server/ServerTable construction touches."""

    def __init__(self, num_workers: int = 2):
        self.rank = 0
        self.num_servers = 1
        self.num_workers = num_workers
        self.sent = []  # (actor name, message), in send order
        self._actors = {}
        self._server = None

    def register_actor(self, actor):
        self._actors[actor.name] = actor

    def deregister_actor(self, actor):
        self._actors.pop(actor.name, None)

    def send_to(self, name, msg):
        self.sent.append((name, msg))

    def register_server_table(self, table) -> int:
        return self._server.register_table(table)


class _StubTable(ServerTable):
    """Host-only table recording every dispatch shape it sees."""

    needs_device_lock = False

    def __init__(self, zoo, eligible: bool = True):
        super().__init__(zoo=zoo)
        self.eligible = eligible
        self.calls = []  # ("get"|"add"|"fused_get"|"fused_add"|"pump", n)
        self.fail_on = None  # value whose serial add raises

    def fuse_eligible(self, blobs, is_get) -> bool:
        return self.eligible

    def process_get(self, blobs):
        self.calls.append(("get", 1))
        return [blobs[0], Blob(np.array([41.0], np.float32))]

    def process_add(self, blobs):
        self.calls.append(("add", 1))
        v = int(blobs[0].as_array(np.int32)[0])
        if self.fail_on is not None and v == self.fail_on:
            raise ValueError(f"poisoned add {v}")

    def process_fused_get(self, requests):
        self.calls.append(("fused_get", len(requests)))
        return [[blobs[0], Blob(np.array([41.0], np.float32))]
                for blobs in requests]

    def process_fused_add(self, requests):
        self.calls.append(("fused_add", len(requests)))
        for i, blobs in enumerate(requests):
            v = int(blobs[0].as_array(np.int32)[0])
            if self.fail_on is not None and v == self.fail_on:
                raise fusion.PartialFuseError(i, ValueError(
                    f"poisoned add {v}"))

    def shard_pump(self):
        self.calls.append(("pump", 0))
        return [], False


def _server_env():
    zoo = _StubZoo()
    server = Server(zoo)
    zoo._server = server
    return zoo, server


def _get(table_id: int, msg_id: int, key: int = 3) -> Message:
    msg = Message(src=1, dst=0, msg_type=MsgType.Request_Get,
                  table_id=table_id, msg_id=msg_id)
    msg.push(Blob(np.array([key], np.int32)))
    return msg


def _add(table_id: int, msg_id: int, key: int = 3) -> Message:
    msg = Message(src=1, dst=0, msg_type=MsgType.Request_Add,
                  table_id=table_id, msg_id=msg_id)
    msg.push(Blob(np.array([key], np.int32)))
    msg.push(Blob(np.array([1.0], np.float32)))
    return msg


def _replies(zoo):
    return [m for name, m in zoo.sent if name == actors.COMMUNICATOR]


class TestPlanner:
    def test_same_table_gets_form_one_group(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        batch = [_get(t.table_id, i) for i in range(3)]
        infos = [fusion.classify(server, i, m)
                 for i, m in enumerate(batch)]
        plan = fusion.split_plan(batch, infos)
        assert len(plan) == 1 and plan[0][0] == "fused"
        (table, is_get, entries), = plan[0][1]
        assert table is t and is_get and len(entries) == 3

    def test_control_and_shard_messages_are_barriers(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        for barrier_type in (MsgType.Server_Shard_Pump,
                             MsgType.Request_ShardData,
                             MsgType.Request_ShardAck,
                             MsgType.Request_FwdGet,
                             MsgType.Request_ReplicaSync):
            msg = Message(src=1, dst=0, msg_type=barrier_type,
                          table_id=t.table_id, msg_id=99)
            assert fusion.classify(server, 0, msg) is None

    def test_empty_payload_get_is_a_barrier(self):
        # Sync-mode clock-tick shards carry no blobs; the serial
        # handler owns their empty-reply protocol.
        zoo, server = _server_env()
        t = _StubTable(zoo)
        msg = Message(src=1, dst=0, msg_type=MsgType.Request_Get,
                      table_id=t.table_id, msg_id=5)
        assert fusion.classify(server, 0, msg) is None

    def test_unknown_table_is_a_barrier(self):
        zoo, server = _server_env()
        assert fusion.classify(server, 0, _get(7, 1)) is None

    def test_ineligible_request_is_a_barrier(self):
        zoo, server = _server_env()
        t = _StubTable(zoo, eligible=False)
        assert fusion.classify(server, 0, _get(t.table_id, 1)) is None

    def test_raising_eligibility_probe_is_a_barrier(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        t.fuse_eligible = None  # not callable: the probe raises
        assert fusion.classify(server, 0, _get(t.table_id, 1)) is None

    def test_barrier_splits_the_window(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        batch = [_get(t.table_id, 1), _get(t.table_id, 2),
                 Message(src=1, dst=0,
                         msg_type=MsgType.Server_Shard_Pump,
                         table_id=t.table_id, msg_id=0),
                 _get(t.table_id, 3)]
        infos = [fusion.classify(server, i, m)
                 for i, m in enumerate(batch)]
        plan = fusion.split_plan(batch, infos)
        assert [step[0] for step in plan] == ["fused", "serial", "fused"]
        assert len(plan[0][1][0][2]) == 2  # first window: two gets
        assert plan[1][1] == 2             # the barrier's batch index
        assert len(plan[2][1][0][2]) == 1

    def test_opposite_op_flushes_the_window(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        batch = [_add(t.table_id, 1), _add(t.table_id, 2),
                 _get(t.table_id, 3), _get(t.table_id, 4)]
        infos = [fusion.classify(server, i, m)
                 for i, m in enumerate(batch)]
        plan = fusion.split_plan(batch, infos)
        assert [step[0] for step in plan] == ["fused", "fused"]
        assert plan[0][1][0][1] is False and len(plan[0][1][0][2]) == 2
        assert plan[1][1][0][1] is True and len(plan[1][1][0][2]) == 2

    def test_two_tables_share_a_window(self):
        zoo, server = _server_env()
        a, b = _StubTable(zoo), _StubTable(zoo)
        batch = [_get(a.table_id, 1), _add(b.table_id, 2),
                 _get(a.table_id, 3)]
        infos = [fusion.classify(server, i, m)
                 for i, m in enumerate(batch)]
        plan = fusion.split_plan(batch, infos)
        # No per-table conflict: one window, two groups, arrival order.
        assert [step[0] for step in plan] == ["fused"]
        groups = plan[0][1]
        assert [(g[0], g[1], len(g[2])) for g in groups] == \
            [(a, True, 2), (b, False, 1)]

    def test_batch_add_is_all_or_nothing(self):
        zoo, server = _server_env()
        good, bad = _StubTable(zoo), _StubTable(zoo, eligible=False)
        subs = [_add(good.table_id, 10), _add(bad.table_id, 11)]
        batch_msg = pack_add_batch(subs)
        assert fusion.classify(server, 0, batch_msg) is None
        all_good = pack_add_batch(
            [_add(good.table_id, 10), _add(good.table_id, 11)])
        entries = fusion.classify(server, 0, all_good)
        assert entries is not None and len(entries) == 2
        assert [e.msg_id for e in entries] == [10, 11]


class TestFusedDispatch:
    def test_fused_execution_and_reply_order_around_a_barrier(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        barrier = _get(t.table_id, 3)  # serial via ineligibility below
        batch = [_get(t.table_id, 1), _get(t.table_id, 2), barrier,
                 _get(t.table_id, 4)]
        orig = t.fuse_eligible
        t.fuse_eligible = \
            lambda blobs, is_get: int(blobs[0].as_array(np.int32)[0]) != 9
        batch[2].data = [Blob(np.array([9], np.int32))]
        server._dispatch_fused(batch)
        t.fuse_eligible = orig
        # One fused program per multi-entry window; the barrier ran
        # serially between them, and the trailing singleton window
        # took the exact serial path (nothing to amortize the fused
        # machinery over — Server._run_fused_group).
        assert t.calls == [("fused_get", 2), ("get", 1), ("get", 1)]
        # Global reply order is arrival order: the deferred fused
        # replies for msgs 1-2 leave BEFORE the barrier's serial reply.
        assert [m.msg_id for m in _replies(zoo)] == [1, 2, 3, 4]
        assert all(take_error(m) is None for m in _replies(zoo))

    def test_shard_pump_mid_batch_executes_between_windows(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        pump = Message(src=0, dst=0,
                       msg_type=MsgType.Server_Shard_Pump,
                       table_id=t.table_id, msg_id=0)
        server._dispatch_fused(
            [_get(t.table_id, 1), pump, _get(t.table_id, 2)])
        # Both windows are singletons (serial path); the pump ran as a
        # barrier between them.
        assert t.calls == [("get", 1), ("pump", 0), ("get", 1)]
        assert [m.msg_id for m in _replies(zoo)] == [1, 2]

    def test_versions_are_monotone_and_post_batch(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        batch = [_add(t.table_id, 1), _add(t.table_id, 2),
                 _get(t.table_id, 3), _add(t.table_id, 4),
                 _get(t.table_id, 5)]
        server._dispatch_fused(batch)
        versions = [reply_version(m) for m in _replies(zoo)]
        # Fused adds stamp the POST-batch version (conservatively late
        # = RYW-safe, docs/SERVER_ENGINE.md): both window-1 adds carry
        # 2; the get between the windows observes exactly those adds.
        assert versions == [2, 2, 2, 3, 3]
        assert versions == sorted(versions)
        assert t.version == 3

    def test_fused_batch_add_reassembles_one_ack(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        parent = pack_add_batch(
            [_add(t.table_id, 20), _add(t.table_id, 21)])
        server._dispatch_fused([parent, _add(t.table_id, 22)])
        assert t.calls == [("fused_add", 3)]
        replies = _replies(zoo)
        assert [m.type for m in replies] == [MsgType.Reply_BatchAdd,
                                             MsgType.Reply_Add]
        desc = replies[0].data[0].as_array(np.int32)
        # [n, (table_id, msg_id, err, version)...] — post-batch
        # version 3 on every sub (core/message.py batch layout).
        assert desc[0] == 2
        assert list(desc[1:9]) == [t.table_id, 20, 0, 3,
                                   t.table_id, 21, 0, 3]
        assert reply_version(replies[1]) == 3

    def test_entry_failure_is_isolated_and_tail_replays(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        t.fail_on = 13
        batch = [_add(t.table_id, 1, key=7), _add(t.table_id, 2, key=13),
                 _add(t.table_id, 3, key=8)]
        server._dispatch_fused(batch)
        # The fused apply stopped at the poisoned entry
        # (PartialFuseError applied=1); the tail replayed serially and
        # the poisoned entry alone failed again there.
        assert t.calls == [("fused_add", 3), ("add", 1), ("add", 1)]
        replies = _replies(zoo)
        assert take_error(replies[0]) is None
        assert "poisoned add 13" in take_error(replies[1])
        assert take_error(replies[2]) is None
        # Version accounting: fused prefix (1) + one serial replay
        # bump; the failed entry bumps nothing.
        assert t.version == 2
        assert reply_version(replies[0]) == 1
        assert reply_version(replies[2]) == 2

    def test_plain_fused_failure_replays_everything(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)

        def explode(requests):
            t.calls.append(("fused_add", len(requests)))
            raise RuntimeError("no prefix applied")

        t.process_fused_add = explode
        server._dispatch_fused(
            [_add(t.table_id, 1), _add(t.table_id, 2)])
        assert t.calls == [("fused_add", 2), ("add", 1), ("add", 1)]
        assert [reply_version(m) for m in _replies(zoo)] == [1, 2]
        assert t.version == 2

    def test_single_message_batches_skip_the_fuse_metric(self):
        from multiverso_tpu.util.dashboard import samples
        zoo, server = _server_env()
        t = _StubTable(zoo)
        before = samples("SERVER_FUSE_BATCH").snapshot()["count"]
        server.receive(_get(t.table_id, 1))
        server.mailbox.exit()
        server._main()
        assert [m.msg_id for m in _replies(zoo)] == [1]
        assert samples("SERVER_FUSE_BATCH").snapshot()["count"] == before

    def test_main_loop_drains_and_fuses(self):
        zoo, server = _server_env()
        t = _StubTable(zoo)
        for i in range(1, 5):
            server.receive(_get(t.table_id, i))
        server.mailbox.exit()
        server._main()
        assert t.calls == [("fused_get", 4)]
        assert [m.msg_id for m in _replies(zoo)] == [1, 2, 3, 4]


class TestSyncForceDisable:
    def test_sync_server_pins_fuse_max_to_one(self):
        set_flag("server_fuse_max", 16)
        set_flag("sync", True)
        try:
            zoo = _StubZoo()
            server = SyncServer(zoo)
            assert server._fuse_max == 1
            assert isinstance(Server.get_server(zoo), SyncServer)
        finally:
            set_flag("sync", False)

    def test_async_server_honors_the_flag(self):
        set_flag("server_fuse_max", 5)
        zoo = _StubZoo()
        assert Server(zoo)._fuse_max == 5


# ---------------------------------------------------------------------------
# integration: fused == serial across the table types
# ---------------------------------------------------------------------------

_N_ADDS = 24  # async adds per worker — enough mailbox pressure to fuse


def _run_cluster(body, fuse_max, extra_argv=()):
    argv = [f"-server_fuse_max={fuse_max}", *extra_argv]
    cluster = LocalCluster(2, argv=argv, roles=["all", "worker"])
    cluster.timeout = 180.0
    return cluster.run(body)


def _matrix_body(is_sparse):
    def body(rank):
        rng = np.random.default_rng(17 + rank)
        table = mv.create_matrix_table(48, 4, np.float32,
                                       is_sparse=is_sparse)
        ids = [rng.integers(0, 48, size=6).astype(np.int32)
               for _ in range(_N_ADDS)]
        # Integer-valued deltas: float32 sums are exact, so any fold
        # order must produce identical bits.
        deltas = [rng.integers(1, 4, size=(6, 4)).astype(np.float32)
                  for _ in range(_N_ADDS)]
        pend = [table.add_rows_async(i, d) for i, d in zip(ids, deltas)]
        for msg_id in pend:
            table.wait(msg_id)
        mv.current_zoo().barrier()
        # Full get FIRST: a sparse whole-table get serves only rows
        # still dirty for this worker, and a row get marks its rows
        # up-to-date (matrix_table.py _up_to_date).
        full = np.array(table.get(), copy=True)
        # Duplicate ids in one request: per-position placement.
        probe = np.array([5, 5, 0, 47, 11], np.int32)
        rows = np.array(table.get_rows(probe), copy=True)
        mv.current_zoo().barrier()
        return full, rows, ids, deltas

    return body


@pytest.mark.parametrize("is_sparse", [False, True],
                         ids=["dense", "sparse"])
def test_matrix_fused_matches_serial_and_exact_sum(is_sparse):
    serial = _run_cluster(_matrix_body(is_sparse), fuse_max=1)
    fused = _run_cluster(_matrix_body(is_sparse), fuse_max=16)
    expected = np.zeros((48, 4), np.float32)
    for _, _, ids, deltas in serial:
        for i, d in zip(ids, deltas):
            np.add.at(expected, i, d)
    for results in (serial, fused):
        for full, rows, _, _ in results:
            np.testing.assert_array_equal(full, expected)
            probe = np.array([5, 5, 0, 47, 11], np.int32)
            np.testing.assert_array_equal(rows, expected[probe])


def test_array_fused_matches_serial_and_exact_sum():
    def body(rank):
        rng = np.random.default_rng(5 + rank)
        table = mv.create_array_table(32, np.float32)
        deltas = [rng.integers(1, 4, size=32).astype(np.float32)
                  for _ in range(_N_ADDS)]
        pend = [table.add_async(d) for d in deltas]
        for msg_id in pend:
            table.wait(msg_id)
        mv.current_zoo().barrier()
        out = np.array(table.get(), copy=True)
        mv.current_zoo().barrier()
        return out, deltas

    serial = _run_cluster(body, fuse_max=1)
    fused = _run_cluster(body, fuse_max=16)
    expected = np.zeros(32, np.float32)
    for _, deltas in serial:
        expected += np.sum(deltas, axis=0)
    for results in (serial, fused):
        for out, _ in results:
            np.testing.assert_array_equal(out, expected)


def test_kv_fused_matches_serial_and_exact_sum():
    def body(rank):
        rng = np.random.default_rng(29 + rank)
        table = mv.create_kv_table()
        keys = [rng.integers(0, 40, size=5).astype(np.int64)
                for _ in range(_N_ADDS)]
        vals = [rng.integers(1, 6, size=5).astype(np.float32)
                for _ in range(_N_ADDS)]
        pend = [table.add_async(k, v) for k, v in zip(keys, vals)]
        for msg_id in pend:
            table.wait(msg_id)
        mv.current_zoo().barrier()
        got = table.get(np.arange(40, dtype=np.int64))
        mv.current_zoo().barrier()
        return got, keys, vals

    serial = _run_cluster(body, fuse_max=1)
    fused = _run_cluster(body, fuse_max=16)
    expected = {k: 0.0 for k in range(40)}
    for _, keys, vals in serial:
        for ks, vs in zip(keys, vals):
            for k, v in zip(ks, vs):
                expected[int(k)] += float(v)
    for results in (serial, fused):
        for got, _, _ in results:
            assert {k: float(v) for k, v in got.items()} == expected


def test_read_your_writes_under_fused_interleaving():
    # Each worker alternates waited Adds with Gets of its own rows: a
    # Get issued after an acked Add must observe AT LEAST that add
    # (fused replies stamp the post-batch version — conservatively
    # late, never early).
    def body(rank):
        table = mv.create_matrix_table(16, 2, np.float32)
        my_row = np.array([rank * 3], np.int32)
        floors = []
        for step in range(1, 9):
            table.add_rows(my_row, np.full((1, 2), 1.0, np.float32))
            rows = table.get_rows(my_row)
            # Own-row sum grows by exactly 1 per waited add; observing
            # less would be a read BEFORE our acked write.
            floors.append(float(rows[0, 0]) >= step)
        mv.current_zoo().barrier()
        return floors

    for floors in _run_cluster(body, fuse_max=16):
        assert all(floors)


def test_chaos_smoke_no_wrong_reads():
    # Reorder + delay data frames while fused traffic flows: every
    # read must still come back exact (fusion is a scheduling change;
    # arrival-order permutations are its everyday input).
    from multiverso_tpu.util import chaos

    def body(rank):
        table = mv.create_matrix_table(24, 2, np.float32)
        ids = np.arange(24, dtype=np.int32)
        pend = [table.add_rows_async(
            ids, np.full((24, 2), 1.0, np.float32))
            for _ in range(_N_ADDS)]
        for msg_id in pend:
            table.wait(msg_id)
        mv.current_zoo().barrier()
        out = np.array(table.get(), copy=True)
        mv.current_zoo().barrier()
        return out

    try:
        results = _run_cluster(
            body, fuse_max=16,
            extra_argv=["-chaos_frames=reorder=0.3,delay_ms=2,"
                        "classes=data,seed=11"])
    finally:
        set_flag("chaos_frames", "")
        chaos._frames_spec = None
    expected = np.full((24, 2), 2.0 * _N_ADDS, np.float32)
    for out in results:
        np.testing.assert_array_equal(out, expected)
