"""Binding tests: Python package, param managers, and the C ABI shim.

Ports the reference's binding test semantics
(ref: binding/python/multiverso/tests/test_multiverso.py:18-60 — array and
matrix handler roundtrips with init_value) and exercises the C ABI
(ref: include/multiverso/c_api.h) through ctypes exactly the way the
reference's utils.Loader does.
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

BINDING_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "binding", "python")
if BINDING_PATH not in sys.path:
    sys.path.insert(0, BINDING_PATH)

import multiverso as mv_binding  # noqa: E402
from multiverso.ext import (JaxParamManager, MVModelParamManager,  # noqa: E402
                            SyncEveryN, TorchParamManager)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB_PATH = os.path.join(REPO, "native", "build", "libmultiverso.so")


def _build_lib() -> bool:
    """Build the c_api shim from source (the .so is a build artifact, not
    checked in); returns whether it is available."""
    if not os.path.exists(LIB_PATH):
        try:
            subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                           capture_output=True, timeout=300, check=False)
        except subprocess.TimeoutExpired:
            return False
    return os.path.exists(LIB_PATH)


@pytest.fixture
def env():
    mv_binding.init()
    yield
    mv_binding.shutdown()


class TestPythonBinding:
    def test_array_handler_roundtrip(self, env):
        # ref: test_multiverso.py array test — init_value lands once
        # (master), adds accumulate.
        init = np.arange(10, dtype=np.float32)
        handler = mv_binding.ArrayTableHandler(10, init_value=init)
        mv_binding.barrier()
        np.testing.assert_array_equal(handler.get(), init)
        handler.add(np.ones(10), sync=True)
        handler.add(np.ones(10), sync=True)
        np.testing.assert_array_equal(handler.get(), init + 2)

    def test_matrix_handler_rows(self, env):
        handler = mv_binding.MatrixTableHandler(6, 4)
        data = np.arange(24, dtype=np.float32).reshape(6, 4)
        handler.add(data, sync=True)
        np.testing.assert_array_equal(handler.get(), data)
        rows = handler.get(row_ids=[1, 3])
        np.testing.assert_array_equal(rows, data[[1, 3]])
        handler.add(np.ones((2, 4)), row_ids=[1, 3], sync=True)
        np.testing.assert_array_equal(handler.get(row_ids=[1]),
                                      data[[1]] + 1)

    def test_api_identity(self, env):
        assert mv_binding.workers_num() == 1
        assert mv_binding.worker_id() == 0
        assert mv_binding.is_master_worker()


class TestParamManagers:
    def test_generic_manager_syncs_deltas(self, env):
        state = {"params": [np.zeros(4, np.float32),
                            np.ones((2, 2), np.float32)]}
        manager = MVModelParamManager(
            lambda: state["params"],
            lambda vals: state.update(params=vals))
        state["params"][0] += 5  # local training step
        manager.sync_all_param()
        np.testing.assert_array_equal(state["params"][0],
                                      np.full(4, 5, np.float32))
        np.testing.assert_array_equal(state["params"][1],
                                      np.ones((2, 2), np.float32))

    def test_sync_every_n(self, env):
        state = {"params": [np.zeros(2, np.float32)]}
        manager = MVModelParamManager(
            lambda: state["params"],
            lambda vals: state.update(params=vals))
        callback = SyncEveryN(manager, n=2)
        state["params"][0] += 1
        callback()  # 1st call: no sync yet
        server = manager.table.get()
        assert server.sum() == 0
        callback()  # 2nd call: syncs
        assert manager.table.get().sum() == pytest.approx(2.0)

    def test_torch_manager(self):
        # torch runs in a SUBPROCESS: importing it next to jax in the
        # long-lived pytest process intermittently SIGABRTs at
        # interpreter teardown (duplicate native runtimes) — observed
        # ~1 in 4 full-suite runs before this isolation.
        import importlib.util
        if importlib.util.find_spec("torch") is None:
            pytest.skip("torch not installed")
        code = (
            f"import sys; sys.path.insert(0, {BINDING_PATH!r})\n"
            "import numpy as np, torch\n"
            "import multiverso as mv_binding\n"
            "from multiverso.ext import TorchParamManager\n"
            "mv_binding.init()\n"
            "module = torch.nn.Linear(3, 2)\n"
            "manager = TorchParamManager(module)\n"
            "with torch.no_grad():\n"
            "    for p in module.parameters():\n"
            "        p.add_(1.0)\n"
            "manager.sync_all_param()\n"
            "merged = [p.detach().numpy() for p in module.parameters()]\n"
            "assert all(np.isfinite(m).all() for m in merged)\n"
            "mv_binding.shutdown()\n"
            "print('TORCH_OK')\n")
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     PYTHONPATH=os.pathsep.join(
                         p for p in (REPO,
                                     os.environ.get("PYTHONPATH", ""))
                         if p)))
        # Assert on the marker, NOT the returncode: the teardown SIGABRT
        # this subprocess exists to dodge fires AFTER the script's own
        # asserts pass and the marker prints.
        assert "TORCH_OK" in out.stdout, out.stderr[-500:]

    def test_jax_manager(self, env):
        import jax.numpy as jnp
        state = {"tree": {"w": jnp.zeros(3), "b": jnp.ones(2)}}
        manager = JaxParamManager(lambda: state["tree"],
                                  lambda t: state.update(tree=t))
        state["tree"] = {"w": state["tree"]["w"] + 2.0,
                         "b": state["tree"]["b"]}
        manager.sync_all_param()
        np.testing.assert_allclose(np.asarray(state["tree"]["w"]),
                                   np.full(3, 2.0))


@pytest.fixture(scope="module")
def shim_lib():
    """Build the .so lazily at test run time (not collection time — a
    skipif condition would compile native code even for --collect-only
    or deselected runs)."""
    if not _build_lib():
        pytest.skip("libmultiverso.so failed to build (make -C native)")
    return LIB_PATH


@pytest.mark.usefixtures("shim_lib")
class TestCApiShim:
    def test_full_roundtrip_in_subprocess(self):
        # Load the shared library the way the reference binding does and
        # drive the whole ABI. Subprocess: the shim init conflicts with an
        # already-initialized zoo in this process.
        code = f"""
import ctypes, numpy as np
lib = ctypes.CDLL({LIB_PATH!r})
args = [b"prog"]
args_t = ctypes.c_char_p * 1
lib.MV_Init(ctypes.pointer(ctypes.c_int(1)), args_t(*args))
assert lib.MV_NumWorkers() == 1
h = ctypes.c_void_p()
lib.MV_NewArrayTable(8, ctypes.byref(h))
data = np.arange(8, dtype=np.float32)
fp = ctypes.POINTER(ctypes.c_float)
lib.MV_AddArrayTable(h, data.ctypes.data_as(fp), 8)
out = np.zeros(8, dtype=np.float32)
lib.MV_GetArrayTable(h, out.ctypes.data_as(fp), 8)
assert (out == data).all(), out
mh = ctypes.c_void_p()
lib.MV_NewMatrixTable(4, 2, ctypes.byref(mh))
rows = np.array([0, 3], dtype=np.int32)
ip = ctypes.POINTER(ctypes.c_int)
vals = np.ones(4, dtype=np.float32)
lib.MV_AddMatrixTableByRows(mh, vals.ctypes.data_as(fp), 4,
                            rows.ctypes.data_as(ip), 2)
allv = np.zeros(8, dtype=np.float32)
lib.MV_GetMatrixTableAll(mh, allv.ctypes.data_as(fp), 8)
assert allv.sum() == 4
lib.MV_Barrier(); lib.MV_ShutDown()
print("C_ABI_OK")
"""
        result = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=240, env=dict(os.environ, PYTHONPATH=REPO))
        assert "C_ABI_OK" in result.stdout, result.stderr[-800:]

    def test_net_bind_connect_in_subprocess(self):
        # MV_NetBind/MV_NetConnect (ref: multiverso.h:55-64): app-driven
        # TCP bootstrap through the C ABI — a single-rank mesh binds,
        # connects to itself, and runs a table roundtrip over TCP.
        code = f"""
import ctypes, socket, numpy as np
s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
lib = ctypes.CDLL({LIB_PATH!r})
ep = f"127.0.0.1:{{port}}".encode()
lib.MV_NetBind(0, ctypes.c_char_p(ep))
ranks = (ctypes.c_int * 1)(0)
eps = (ctypes.c_char_p * 1)(ep)
lib.MV_NetConnect(ranks, eps, 1)
args = [b"prog"]
lib.MV_Init(ctypes.pointer(ctypes.c_int(1)), (ctypes.c_char_p * 1)(*args))
h = ctypes.c_void_p()
lib.MV_NewArrayTable(4, ctypes.byref(h))
fp = ctypes.POINTER(ctypes.c_float)
data = np.full(4, 2.0, dtype=np.float32)
lib.MV_AddArrayTable(h, data.ctypes.data_as(fp), 4)
out = np.zeros(4, dtype=np.float32)
lib.MV_GetArrayTable(h, out.ctypes.data_as(fp), 4)
assert (out == 2.0).all(), out
lib.MV_ShutDown()
print("NET_BIND_OK")
"""
        result = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=240, env=dict(os.environ, PYTHONPATH=REPO,
                                  JAX_PLATFORMS="cpu"))
        assert "NET_BIND_OK" in result.stdout, result.stderr[-800:]

    def test_csharp_binding_abi(self):
        # The C# binding is pure P/Invoke source (ref: the CLR wrapper's
        # surface, binding/C#/MultiversoCLR/MultiversoCLR.h:11-45). No
        # .NET SDK ships in this image, so validate structurally: every
        # DllImport EntryPoint must exist in the built .so, and the
        # wrapper facade must exercise the full native surface.
        import re
        cs_dir = os.path.join(REPO, "binding", "csharp", "Multiverso")
        with open(os.path.join(cs_dir, "NativeMethods.cs")) as f:
            native_src = f.read()
        entry_points = re.findall(r'EntryPoint = "(\w+)"', native_src)
        assert len(entry_points) >= 16, entry_points
        lib = ctypes.CDLL(LIB_PATH)
        for symbol in entry_points:
            assert getattr(lib, symbol, None) is not None, \
                f"{symbol} declared in NativeMethods.cs but not exported"
        with open(os.path.join(cs_dir, "MultiversoWrapper.cs")) as f:
            wrapper_src = f.read()
        used = set(re.findall(r"NativeMethods\.(\w+)", wrapper_src))
        assert used == set(entry_points), \
            f"wrapper does not cover the ABI: missing {set(entry_points) - used}"
        # If an SDK happens to be present, actually compile the project.
        import shutil
        if shutil.which("dotnet"):
            result = subprocess.run(
                ["dotnet", "build", "-nologo"], cwd=cs_dir,
                capture_output=True, text=True, timeout=300)
            assert result.returncode == 0, result.stdout[-800:]

    def test_lua_binding(self):
        # The LuaJIT FFI binding drives the same .so (ref: binding/lua/).
        # The test image ships no Lua runtime; the binding is validated
        # here when one exists and in CI images that carry luajit.
        import shutil
        lua = next((exe for exe in ("luajit", "lua5.1", "lua")
                    if shutil.which(exe)), None)
        if lua is None:
            pytest.skip("no Lua runtime in this image")
        result = subprocess.run(
            [lua, "test.lua"], cwd=os.path.join(REPO, "binding", "lua"),
            capture_output=True, text=True, timeout=240,
            env=dict(os.environ, PYTHONPATH=REPO,
                     MULTIVERSO_LIB=LIB_PATH))
        assert "LUA_BINDING_OK" in result.stdout, \
            result.stdout[-400:] + result.stderr[-800:]


class TestExamples:
    """The shipped binding examples must actually run (the reference's
    theano/keras examples double as smoke tests in its CI,
    ref: deploy/docker/Dockerfile:96-99)."""

    def _run(self, name, workers):
        example = os.path.join(BINDING_PATH, "examples", name)
        result = subprocess.run(
            [sys.executable, example, f"-workers={workers}"],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, PYTHONPATH=os.pathsep.join(
                [REPO, BINDING_PATH])),
            cwd=REPO)
        assert result.returncode == 0, result.stderr[-1200:]
        return result.stdout

    def test_jax_logreg_example_two_workers(self):
        out = self._run("jax_logistic_regression.py", 2)
        accs = [float(a.strip("'\" ,[]")) for a in
                out.split("accuracy:")[1].split()]
        assert all(a > 0.8 for a in accs), out  # learns, not just runs

    def test_torch_mlp_example_two_workers(self):
        import importlib.util
        if importlib.util.find_spec("torch") is None:
            pytest.skip("torch not installed")  # find_spec, not import:
        # loading torch into the pytest process intermittently aborts
        # at teardown next to jax
        out = self._run("torch_mlp.py", 2)
        assert "accuracy" in out, out
