"""Client-cache tests: versioned replies, staleness bound, prefetch.

Covers the worker-side parameter cache (tables/client_cache.py):
default-off byte-identical behavior, row-cache hits that bypass the
wire, read-your-writes via ack-resolved self-invalidation, the
staleness-bound property (a cached Get never serves a version older
than latest-observed minus -max_get_staleness), in-flight Get
deduplication, prefetch, BSP force-disable, and the Array/KV variants.
"""

import threading

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.dashboard import Dashboard


@pytest.fixture
def env():
    mv.init([])
    yield
    mv.shutdown()


@pytest.fixture
def cache_env():
    """Cache enabled with a staleness bound of 4 applied Adds."""
    mv.init([])
    set_flag("max_get_staleness", 4)
    yield
    mv.shutdown()


def _server_gets() -> int:
    return Dashboard.get("SERVER_PROCESS_GET").count


class TestDisabledByDefault:
    def test_no_cache_objects_without_flag(self, env):
        matrix = mv.create_matrix_table(16, 4)
        array = mv.create_array_table(16)
        kv = mv.create_kv_table()
        # The matrix row cache is now ALWAYS constructed (so a live
        # Control_Config can activate it, docs/AUTOTUNE.md) but must
        # be INACTIVE — the pass-through contract the tests below
        # pin. Array/KV caches stay construction-gated.
        assert matrix._row_cache is not None
        assert not matrix._row_cache.active
        assert matrix._live_cache() is None
        assert array._blob_cache is None
        assert kv._snap_cache is None

    def test_every_get_takes_the_wire(self, env):
        table = mv.create_matrix_table(16, 4)
        table.add(np.ones((16, 4), np.float32))
        ids = np.array([1, 2], np.int32)
        before = _server_gets()
        table.get_rows(ids)
        table.get_rows(ids)
        assert _server_gets() - before == 2

    def test_prefetch_is_a_noop_when_disabled(self, env):
        table = mv.create_matrix_table(16, 4)
        before = _server_gets()
        mid = table.prefetch_rows_async(np.array([1, 2], np.int32))
        assert table.wait(mid, timeout=10)
        assert _server_gets() - before == 0

    def test_sync_mode_force_disables(self):
        # BSP: a locally served Get would bypass the sync server's
        # vector clocks — the flag must not matter.
        mv.init(["-sync=true", "-max_get_staleness=8"])
        try:
            table = mv.create_matrix_table(8, 2)
            assert table._row_cache is None  # sync: never constructed
            # — no hook exists, so no live config can ever enable it
            table.add(np.ones((8, 2), np.float32))
            out = table.get_rows(np.array([3], np.int32))
            np.testing.assert_array_equal(out, np.ones((1, 2)))
        finally:
            mv.shutdown()


class TestRowCache:
    def test_repeat_get_hits_locally(self, cache_env):
        table = mv.create_matrix_table(32, 4)
        base = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        table.add(base)
        ids = np.array([1, 5, 5, 31], np.int32)  # dups welcome
        before = _server_gets()
        first = table.get_rows(ids).copy()
        hit = table.get_rows(ids).copy()
        np.testing.assert_array_equal(first, base[ids])
        np.testing.assert_array_equal(hit, base[ids])
        assert _server_gets() - before == 1  # second get never left
        assert table._row_cache.hits == 1

    def test_versions_ride_replies(self, cache_env):
        table = mv.create_matrix_table(8, 2)
        for i in range(3):
            table.add(np.ones((8, 2), np.float32))
        # Single in-process server = server id 0; three acked adds.
        assert table._version_tracker.latest(0) == 3
        table.get_rows(np.array([0], np.int32))
        assert table._version_tracker.latest(0) == 3

    def test_read_your_writes(self, cache_env):
        table = mv.create_matrix_table(16, 4)
        base = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
        table.add(base)
        ids = np.array([2, 7], np.int32)
        table.get_rows(ids)  # populate
        table.add_rows(np.array([7], np.int32),
                       np.ones((1, 4), np.float32))
        # The own write must be visible immediately — the cached copy
        # of row 7 was invalidated at issue and its floor raised by the
        # ack, so this get refetches.
        got = table.get_rows(ids)
        np.testing.assert_array_equal(got[0], base[2])
        np.testing.assert_array_equal(got[1], base[7] + 1.0)

    def test_whole_table_add_invalidates(self, cache_env):
        table = mv.create_matrix_table(8, 2)
        ids = np.array([1, 3], np.int32)
        table.get_rows(ids)  # populate at version 0
        table.add(np.full((8, 2), 5.0, np.float32))
        got = table.get_rows(ids)
        np.testing.assert_array_equal(got, np.full((2, 2), 5.0))

    def test_staleness_bound_property(self, cache_env):
        # THE acceptance property: a cached Get never serves a version
        # older than latest-observed - max_get_staleness. Randomized
        # add/get interleaving against a shadow model; every served row
        # is checked via the cache's on_hit hook, and (single worker =
        # every add is an own-add) every get must equal the shadow
        # exactly.
        rng = np.random.default_rng(17)
        table = mv.create_matrix_table(24, 3)
        bound = table._row_cache._bound
        served = []

        def on_hit(row, entry_version, latest, k):
            served.append((row, entry_version, latest, k))
            assert entry_version >= latest - k, \
                (row, entry_version, latest, k)

        table._row_cache.on_hit = on_hit
        shadow = np.zeros((24, 3), np.float32)
        for step in range(80):
            if rng.random() < 0.4:
                rows = np.unique(rng.integers(0, 24, size=3)) \
                    .astype(np.int32)
                delta = rng.normal(size=(rows.size, 3)) \
                    .astype(np.float32)
                table.add_rows(rows, delta)
                shadow[rows] += delta
            else:
                rows = np.unique(rng.integers(0, 24, size=4)) \
                    .astype(np.int32)
                got = table.get_rows(rows)
                np.testing.assert_allclose(got, shadow[rows],
                                           rtol=0, atol=1e-5)
        assert served, "no cached Get ever served — cache inert"
        assert all(v >= latest - bound for _, v, latest, _ in served)

    def test_capacity_eviction(self, cache_env):
        from multiverso_tpu.tables.client_cache import RowCache
        table = mv.create_matrix_table(64, 2)
        table._row_cache = RowCache(
            4, table._row_cache._server_of, 1,
            table._version_tracker, capacity=8)
        table.add(np.ones((64, 2), np.float32))
        for lo in range(0, 64, 8):
            table.get_rows(np.arange(lo, lo + 8, dtype=np.int32))
        assert len(table._row_cache._rows) <= 8


class TestPrefetchAndDedup:
    def test_prefetch_then_get_is_local(self, cache_env):
        table = mv.create_matrix_table(32, 4)
        base = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        table.add(base)
        ids = np.array([3, 9], np.int32)
        before = _server_gets()
        mid = table.prefetch_rows_async(ids)
        assert table.wait(mid, timeout=10)
        got = table.get_rows(ids)
        np.testing.assert_array_equal(got, base[ids])
        assert _server_gets() - before == 1  # only the prefetch went out

    def test_inflight_dedup_single_wire_get(self, cache_env):
        # A Get issued while a prefetch for the same rows is in flight
        # must join it (or hit the already-landed cache): exactly ONE
        # server-side Get either way, and the values are exact.
        table = mv.create_matrix_table(32, 4)
        base = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        table.add(base)
        ids = np.array([4, 11], np.int32)
        before = _server_gets()
        table.prefetch_rows_async(ids)  # not waited: maybe in flight
        got = table.get_rows(ids)
        np.testing.assert_array_equal(got, base[ids])
        assert _server_gets() - before == 1

    def test_duplicate_prefetches_dedup(self, cache_env):
        table = mv.create_matrix_table(32, 4)
        table.add(np.ones((32, 4), np.float32))
        ids = np.array([6, 13], np.int32)
        before = _server_gets()
        mids = {table.prefetch_rows_async(ids) for _ in range(4)}
        for mid in mids:
            assert table.wait(mid, timeout=10)
        # All four returned ids resolve, but at most one hit the wire
        # (later calls either dedup to the in-flight id or see the
        # landed cache).
        assert _server_gets() - before <= 1

    def test_joined_get_falls_back_after_invalidation(self, cache_env):
        # Pathological interleave: join an in-flight prefetch, then the
        # rows get invalidated by an own add before completion — the
        # joined Get must still complete with fresh values (forwarded
        # to the wire), never hang or serve the pre-add row.
        table = mv.create_matrix_table(16, 2)
        table.add(np.ones((16, 2), np.float32))
        ids = np.array([5], np.int32)
        pf = table.prefetch_rows_async(ids)
        table.wait(pf, timeout=10)
        # Simulate the deferred path directly: register a join (with
        # the destination registers a real get_rows_async would have
        # set), block the row, then run the completion handler.
        out = np.empty((1, 2), np.float32)
        mid = table._new_request()
        table._dest, table._dest_rows = out, ids
        table._device_shards = None
        table._pf_rows[99] = ids
        table._pf_joined[99] = [(mid, ids, out)]
        tok = table._row_cache.begin_add(ids)  # invalidates row 5
        table._on_prefetch_done(99)
        table._row_cache.finish_add(tok)
        assert table.wait(mid, timeout=10)
        np.testing.assert_array_equal(out, np.ones((1, 2)))


class TestArrayAndKV:
    def test_array_blob_cache_roundtrip(self, cache_env):
        table = mv.create_array_table(64)
        table.add(np.ones(64, np.float32))
        before = _server_gets()
        first = table.get().copy()
        hit = table.get().copy()
        np.testing.assert_array_equal(first, hit)
        assert _server_gets() - before == 1
        # Own add invalidates; the next get refetches the new state.
        table.add(np.ones(64, np.float32))
        np.testing.assert_array_equal(table.get(),
                                      2 * np.ones(64, np.float32))

    def test_array_prefetch(self, cache_env):
        table = mv.create_array_table(32)
        table.add(np.full(32, 3.0, np.float32))
        before = _server_gets()
        mid = table.prefetch_async()
        assert table.wait(mid, timeout=10)
        np.testing.assert_array_equal(table.get(),
                                      np.full(32, 3.0, np.float32))
        assert _server_gets() - before == 1

    def test_kv_snapshot_cache(self, cache_env):
        table = mv.create_kv_table()
        table.add([1, 9], [1.0, 2.0])
        before = _server_gets()
        assert table.get([1, 9])[1] == pytest.approx(1.0)
        assert table.get([1, 9])[9] == pytest.approx(2.0)
        assert _server_gets() - before == 1
        table.add([1], [10.0])
        assert table.get([1, 9])[1] == pytest.approx(11.0)


class TestMultiServer:
    def test_two_servers_cache_correctness(self):
        # Rows spanning both servers' ranges: per-server version
        # tracking, own-write visibility, and hits across shards.
        def body(rank):
            table = mv.create_matrix_table(10, 3)
            zoo = mv.current_zoo()
            base = np.arange(30, dtype=np.float32).reshape(10, 3)
            if rank == 0:
                table.add(base)
            zoo.barrier()
            ids = np.array([1, 8], np.int32)  # one row per server
            first = table.get_rows(ids).copy()
            hit = table.get_rows(ids).copy()
            ok = (np.array_equal(first, base[ids])
                  and np.array_equal(hit, base[ids]))
            zoo.barrier()
            if rank == 1:
                table.add_rows(ids, np.ones((2, 3), np.float32))
                own = table.get_rows(ids)  # read-your-writes, 2 shards
                ok = ok and np.array_equal(own, base[ids] + 1.0)
            zoo.barrier()
            return ok, table._row_cache.hits

        results = LocalCluster(2, argv=["-max_get_staleness=4"]).run(body)
        assert all(ok for ok, _ in results)
        assert all(hits >= 1 for _, hits in results)

    def test_bounded_staleness_under_peer_writes(self):
        # A peer's adds bump the version; once this worker OBSERVES the
        # newer version (via its own traffic), entries older than the
        # bound stop serving. With bound=1 and two observed peer adds,
        # the cached entry must be refetched.
        def body(rank):
            table = mv.create_matrix_table(8, 2)
            zoo = mv.current_zoo()
            ids = np.array([2], np.int32)
            if rank == 0:
                table.get_rows(ids)  # cache at version 0
            zoo.barrier()
            if rank == 1:
                for _ in range(2):
                    table.add_rows(ids, np.ones((1, 2), np.float32))
            zoo.barrier()
            if rank == 0:
                # Observe the head version through an uncached row of
                # the SAME server shard (rows 0-3 on server 0; version
                # stamps are per shard), then the stale entry (2
                # versions behind > bound 1) must miss and refetch.
                table.get_rows(np.array([3], np.int32))
                got = table.get_rows(ids)
                return got.tolist()
            return None

        results = LocalCluster(
            2, argv=["-max_get_staleness=1"]).run(body)
        assert results[0] == [[2.0, 2.0]]


class TestPSTrainerPrefetch:
    def test_host_path_trainer_prefetches_and_trains(self, tmp_path):
        # The wordembedding PS loop's double-buffer: with the cache on
        # and the host (wire-shaped) path forced, train_batches must
        # issue prefetches for batch i+1 while batch i runs, and the
        # model must still train (finite decreasing-ish loss, moved
        # embeddings).
        from multiverso_tpu.models.wordembedding import (
            Dictionary, PSWord2Vec, Word2VecConfig, iter_pair_batches)
        path = tmp_path / "corpus.txt"
        rng = np.random.default_rng(0)
        words = [f"w{i}" for i in range(30)]
        path.write_text("\n".join(
            " ".join(rng.choice(words, size=12)) for _ in range(120)))
        mv.init([])
        set_flag("max_get_staleness", 8)
        d = Dictionary.build(str(path), min_count=1)
        config = Word2VecConfig(embedding_size=8, window=2, epochs=1,
                                negative=2, sample=0, batch_size=256)
        model = PSWord2Vec(config, d)
        # Force the host-buffer pull/push path (in-process tests are
        # device-path by default; remote workers take this branch).
        model._device_path = False
        model._use_prefetch = True
        before = Dashboard.get("CLIENT_CACHE_PREFETCH").count
        loss, pairs = model.train_batches(iter_pair_batches(
            d, str(path), batch_size=256, window=2, subsample=0))
        assert np.isfinite(loss) and pairs > 0
        assert Dashboard.get("CLIENT_CACHE_PREFETCH").count > before
        emb = model.embeddings
        assert np.abs(emb).sum() > 0
        mv.shutdown()


class TestErrorReaping:
    def test_fire_and_forget_failures_bounded(self, env):
        # Satellite: never-waited failed requests must not leak error
        # entries until shutdown.
        from multiverso_tpu.core.blob import Blob
        from multiverso_tpu.tables import table_interface as ti
        table = mv.create_matrix_table(8, 2)
        cap = ti._MAX_RETAINED_ERRORS
        for i in range(cap + 60):
            # Raw API bypasses caller-side checks; partition fails in
            # the worker actor and records an error nobody waits for.
            table.get_async_raw(
                Blob(np.array([-9], np.int32).view(np.uint8)))
        # Drain: a waited request forces the worker actor through the
        # backlog before we inspect.
        table.add(np.ones((8, 2), np.float32))
        assert len(table._errors) <= cap + 1
        # The table remains fully usable and errors still surface for
        # requests that ARE waited.
        from multiverso_tpu.tables.table_interface import \
            TableRequestError
        mid = table.get_async_raw(
            Blob(np.array([-9], np.int32).view(np.uint8)))
        with pytest.raises(TableRequestError):
            table.wait(mid)
