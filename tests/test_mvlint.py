"""Self-tests for the mvlint static-analysis suite (tools/mvlint).

Each pass runs over a fixture file with seeded violations
(tools/mvlint/fixtures/) so the analyzers themselves are
regression-protected: a pass that silently stops firing breaks these
counts, and a pass that starts over-firing breaks the clean-tree gate.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

from tools.mvlint import REPO_ROOT, build_passes, run
from tools.mvlint.framework import ModuleInfo, run_passes
from tools.mvlint.wire_slot_lint import WireSlotLint, parse_doc_slots

FIXTURES = Path(__file__).parent.parent / "tools" / "mvlint" / "fixtures"


def _fixture_result(name: str):
    return run_passes(build_passes(REPO_ROOT),
                      [str(FIXTURES / name)], REPO_ROOT)


class TestFixtures:
    def test_flag_lint_seeded(self):
        result = _fixture_result("bad_flags.py")
        found = [v for v in result.violations
                 if v.pass_name == "flag-lint"]
        assert len(found) == 4, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        # The typo diagnostic names the nearest real flag.
        assert "did you mean 'allreduce_window'" in messages
        assert "default drift" in messages
        assert "drifts from the canonical default 32" in messages
        assert result.per_pass_suppressed["flag-lint"] == 1

    def test_wire_slot_seeded(self):
        result = _fixture_result("bad_wire_slots.py")
        found = [v for v in result.violations
                 if v.pass_name == "wire-slot"]
        assert len(found) == 3, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        assert "raw header[5]" in messages
        assert "'MY_SLOT'" in messages
        assert "computed header index" in messages
        assert result.per_pass_suppressed["wire-slot"] == 1

    def test_device_dispatch_seeded(self):
        result = _fixture_result("bad_device_train.py")
        found = [v for v in result.violations
                 if v.pass_name == "device-dispatch"]
        # Exactly the three unguarded eager sites; everything guarded,
        # traced (decorated / jit-by-name / called-from-traced), or
        # pragma'd stays silent.
        assert len(found) == 3, [v.render() for v in found]
        lines = sorted(v.line for v in found)
        src = (FIXTURES / "bad_device_train.py").read_text().splitlines()
        for line in lines:
            assert "# A" in src[line - 1] or "# B" in src[line - 1] \
                or "# C" in src[line - 1], src[line - 1]
        assert result.per_pass_suppressed["device-dispatch"] == 1

    def test_fused_device_dispatch_seeded(self):
        # PR-19 regression fixture: fused dispatch sites (one device
        # program for MANY requests, runtime/fusion.py) are ordinary
        # call sites to the pass — an unguarded fused concat+gather is
        # flagged exactly like a serial one, and the _lock_for guard
        # Server._run_fused_group holds keeps the real path silent.
        result = _fixture_result("bad_fused_device_train.py")
        found = [v for v in result.violations
                 if v.pass_name == "device-dispatch"]
        assert len(found) == 2, [v.render() for v in found]
        src = (FIXTURES / "bad_fused_device_train.py") \
            .read_text().splitlines()
        for line in sorted(v.line for v in found):
            assert "# D" in src[line - 1] or "# E" in src[line - 1], \
                src[line - 1]
        assert result.per_pass_suppressed["device-dispatch"] == 1

    def test_lock_discipline_seeded(self):
        result = _fixture_result("bad_locks.py")
        found = [v for v in result.violations
                 if v.pass_name == "lock-discipline"]
        assert len(found) == 7, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        assert "bare .acquire()" in messages
        assert "bare .release()" in messages
        assert "blocking call .pop" in messages
        assert "blocking call .join" in messages
        assert "blocking call .wait(" in messages
        # wait_for's mandatory predicate must not read as a timeout.
        assert "blocking call .wait_for" in messages
        # socket.recv's bufsize must not read as a timeout either.
        assert "blocking call .recv" in messages
        assert result.per_pass_suppressed["lock-discipline"] == 1

    def test_metric_name_seeded(self):
        result = _fixture_result("bad_metrics.py")
        found = [v for v in result.violations
                 if v.pass_name == "metric-name"]
        assert len(found) == 3, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        # The typo diagnostic names the nearest real metric.
        assert "did you mean 'SERVER_PROCESS_GET'" in messages
        assert "DISPATCH_MS[q9]" in messages
        assert "TOTALLY_MADE_UP_COUNTER" in messages
        # The family instance and the str.count attribute call in the
        # fixture stay silent; the pragma'd site counts as suppressed.
        assert result.per_pass_suppressed["metric-name"] == 1

    def test_send_discipline_seeded(self):
        result = _fixture_result("bad_sends.py")
        found = [v for v in result.violations
                 if v.pass_name == "send-discipline"]
        assert len(found) == 2, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        assert "send_async" in messages
        # send_async, socket.send and generator.send stay silent; the
        # pragma'd site counts as suppressed.
        assert result.per_pass_suppressed["send-discipline"] == 1

    def test_tunable_lint_seeded(self):
        result = _fixture_result("bad_tunables.py")
        found = [v for v in result.violations
                 if v.pass_name == "tunable-lint"]
        assert len(found) == 2, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        assert "did you mean 'max_get_staleness'" in messages
        assert "'port'" in messages

    def test_copy_lint_seeded(self):
        result = _fixture_result("bad_copies.py")
        found = [v for v in result.violations
                 if v.pass_name == "copy-lint"]
        assert len(found) == 3, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        assert ".tobytes() copies the whole payload" in messages
        assert "bytes-join builds a flat frame copy" in messages
        assert "bytes(...) copies its buffer" in messages
        # memoryview/frombuffer view reads and no-arg bytes() stay
        # silent; the pragma'd legacy-path site counts as suppressed.
        assert result.per_pass_suppressed["copy-lint"] == 1

    def test_copy_lint_out_of_scope_module_is_silent(self):
        # The ban applies to the wire-path modules only: the same
        # patterns in a fixture scanned under a non-wire rel path stay
        # silent for every OTHER fixture (which all use bytes/joins
        # freely in their own seeded content).
        result = _fixture_result("bad_flags.py")
        assert not [v for v in result.violations
                    if v.pass_name == "copy-lint"]

    def test_thread_role_seeded(self):
        result = _fixture_result("bad_roles.py")
        found = [v for v in result.violations
                 if v.pass_name == "thread-role"]
        assert len(found) == 5, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        # The PR-6 regression, interprocedurally: the blocking send
        # sits two helpers below the LIVENESS entry, and the chain
        # names every hop.
        assert "blocking net.send() reachable" in messages
        assert "LIVENESS" in messages
        assert "_hb_main -> bad_roles.py:SeededMonitor._emit" \
            in messages
        assert "raw threading.Thread()" in messages
        assert "not a literal role constant" in messages
        assert "without a role" in messages
        assert "does not resolve" in messages
        assert result.per_pass_suppressed["thread-role"] == 1

    def test_guarded_by_seeded(self):
        result = _fixture_result("bad_guards.py")
        found = [v for v in result.violations
                 if v.pass_name == "guarded-by"]
        assert len(found) == 3, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        assert "registers no such lock with the witness" in messages
        # Off-lock direct access, and the helper whose caller holds
        # nothing; the caller-holds helper (_bump) stays silent.
        assert "in SeededCache.bad_read()" in messages
        assert "in SeededCache._store()" in messages
        assert "_bump" not in messages
        assert result.per_pass_suppressed["guarded-by"] == 1

    def test_msg_flow_seeded(self):
        result = _fixture_result("bad_msg_flow.py")
        found = [v for v in result.violations
                 if v.pass_name == "msg-flow"]
        assert len(found) == 4, [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        # Duplicate registration names the shadowed first site.
        assert "duplicate register_handler" in messages
        assert "first at tools/mvlint/fixtures/bad_msg_flow.py:24" \
            in messages
        # Reply handler that never counts the waiter down.
        assert "never reaches Waiter.notify/release" in messages
        # Reply handler that ignores the error path.
        assert "never inspects take_error()" in messages
        # Request nobody answers.
        assert "none reaches create_reply_message()" in messages
        assert result.per_pass_suppressed["msg-flow"] == 1

    def test_wake_protocol_seeded(self):
        result = _fixture_result("bad_wake_protocol.py")
        found = [v for v in result.violations
                 if v.pass_name == "wake-protocol"]
        assert len(found) == 3, [v.render() for v in found]
        lines = sorted(v.line for v in found)
        assert lines == [39, 58, 74], [v.render() for v in found]
        messages = "\n".join(v.message for v in found)
        assert "re-armed AFTER a state check" in messages
        assert "re-armed AFTER the park" in messages
        assert "never re-arms wake latch" in messages
        # Every diagnostic teaches the fix, not just the fault.
        assert "re-arm first, then check state, then park" in messages
        assert result.per_pass_suppressed["wake-protocol"] == 1

    def test_fixture_dir_fails_as_a_whole(self):
        result = run_passes(build_passes(REPO_ROOT), [str(FIXTURES)],
                            REPO_ROOT)
        assert result.failed
        assert len(result.violations) == 44
        assert len(result.suppressed) == 13


class TestCleanTree:
    def test_final_tree_is_clean(self):
        # The acceptance gate: the shipped tree has zero non-pragma'd
        # violations across all ten passes.
        result = run(("multiverso_tpu", "tests", "bench.py"), REPO_ROOT)
        assert not result.failed, \
            "\n".join(v.render() for v in result.violations)

    def test_doc_slot_table_matches_registry(self):
        doc = parse_doc_slots(REPO_ROOT / "docs" / "WIRE_FORMAT.md")
        from multiverso_tpu.core.message import WIRE_SLOTS
        assert doc == WIRE_SLOTS

    def test_doc_msg_type_table_matches_registry(self):
        from multiverso_tpu.core.message import MsgType
        from tools.mvlint.wire_slot_lint import parse_doc_msg_types
        doc = parse_doc_msg_types(REPO_ROOT / "docs" / "WIRE_FORMAT.md")
        enum = {t.name: int(t) for t in MsgType if t.name != "Default"}
        assert doc == enum

    def test_msg_type_doc_drift_is_a_violation(self, tmp_path):
        drifted = tmp_path / "WIRE_FORMAT.md"
        drifted.write_text("| 5 | `ERROR_SLOT` |\n"
                           "| `Request_Get` | 1 |\n"
                           "| `Ghost_Type` | 99 |\n")
        lint = WireSlotLint({"ERROR_SLOT": 5}, drifted,
                            msg_types={"Request_Get": 1,
                                       "Request_Add": 2})
        module = ModuleInfo(FIXTURES / "bad_flags.py", REPO_ROOT)
        messages = [v.message for v in lint.check(module)]
        assert any("Request_Add=2 missing" in m for m in messages)
        assert any("Ghost_Type" in m for m in messages)

    def test_doc_flow_table_covers_every_msg_type(self):
        from multiverso_tpu.core.message import MsgType
        from tools.mvlint.msg_flow_lint import load_flow_table
        flow = load_flow_table(REPO_ROOT / "docs" / "WIRE_FORMAT.md")
        assert set(flow) == {t.name for t in MsgType}
        for name, (kind, paired, _handlers, _line) in flow.items():
            assert kind in {"request", "reply", "fire-and-forget"}, name
            if kind == "request":
                # Every request names its reply, and the reply row
                # agrees — pairing is by table, not value arithmetic
                # (Request_FwdGet=9 pairs Reply_Get=-1).
                assert paired in flow, name
                assert flow[paired][0] == "reply", name

    def test_flow_table_doc_drift_is_a_violation(self):
        # Both directions fire: a MsgType with no flow row, and a
        # stale flow row naming no MsgType member.
        lint = next(p for p in build_passes(REPO_ROOT)
                    if p.name == "msg-flow")
        lint.flow = dict(lint.flow)
        del lint.flow["Request_Get"]
        lint.flow["Ghost_Message"] = ("fire-and-forget", None, (), 1)
        messages = [v.message for v in lint._doc_checks()]
        assert any("MsgType.Request_Get" in m and "no row" in m
                   for m in messages)
        assert any("Ghost_Message" in m and "no MsgType member" in m
                   for m in messages)

    def test_flow_table_handler_drift_is_a_violation(self):
        # The table's handler column is checked against the COMPUTED
        # register_handler/intercept sites, both directions.
        lint = next(p for p in build_passes(REPO_ROOT)
                    if p.name == "msg-flow")
        lint.flow = dict(lint.flow)
        kind, paired, _handlers, line = lint.flow["Control_Heartbeat"]
        lint.flow["Control_Heartbeat"] = (kind, paired, ("shm",), line)
        messages = [v.message for v in lint._doc_checks()]
        assert any("Control_Heartbeat" in m
                   and "declares handlers [shm]" in m
                   and "computes [controller]" in m
                   for m in messages)

    def test_doc_metric_table_matches_registry(self):
        from tools.mvlint.metric_lint import (load_metric_names,
                                             parse_doc_metrics)
        doc = parse_doc_metrics(REPO_ROOT / "docs" / "OBSERVABILITY.md")
        registry = load_metric_names(
            REPO_ROOT / "multiverso_tpu" / "util" / "dashboard.py")
        assert set(doc) == set(registry)

    def test_metric_doc_drift_is_a_violation(self, tmp_path):
        from tools.mvlint.metric_lint import MetricNameLint
        drifted = tmp_path / "OBSERVABILITY.md"
        drifted.write_text(
            "| `SERVER_PROCESS_GET` | monitor | fine |\n"
            "| `GHOST_METRIC` | counter | stale doc row |\n")
        lint = MetricNameLint({"SERVER_PROCESS_GET": "x",
                               "NEVER_DOCUMENTED": "y"}, drifted)
        module = ModuleInfo(FIXTURES / "bad_flags.py", REPO_ROOT)
        found = list(lint.check(module))
        messages = "\n".join(v.message for v in found)
        assert "GHOST_METRIC" in messages          # doc-only row
        assert "NEVER_DOCUMENTED" in messages      # registry-only name
        assert len(found) == 2

    def test_doc_thread_table_matches_registry(self):
        from tools.mvlint.role_lint import (load_doc_roles,
                                            load_thread_roles)
        doc = load_doc_roles(REPO_ROOT)
        registry, _ = load_thread_roles(REPO_ROOT)
        assert {e: r for e, (r, _) in doc.items()} == registry

    def test_thread_doc_drift_is_a_violation(self):
        # _doc_direction fires both ways: a registry entry with no
        # docs/THREADS.md row, and a stale doc row with no entry.
        lint = next(p for p in build_passes(REPO_ROOT)
                    if p.name == "thread-role")
        lint.doc_roles = dict(lint.doc_roles)
        entry = sorted(lint.doc_roles)[0]
        del lint.doc_roles[entry]
        lint.doc_roles["runtime/ghost.py::Ghost._main"] = ("ACTOR", 999)
        messages = [v.message for v in lint._doc_direction()]
        assert any(entry in m and "no row" in m for m in messages)
        assert any("Ghost._main" in m and "stale" in m
                   for m in messages)

    def test_doc_wire_path_table_matches_lint(self):
        from tools.mvlint.copy_lint import (WIRE_PATH_MODULES,
                                            parse_doc_modules)
        doc = parse_doc_modules(REPO_ROOT / "docs" / "MEMORY.md")
        assert set(doc) == set(WIRE_PATH_MODULES)

    def test_copy_lint_doc_drift_is_a_violation(self, tmp_path):
        from tools.mvlint.copy_lint import CopyLint
        drifted = tmp_path / "MEMORY.md"
        drifted.write_text(
            "| `multiverso_tpu/runtime/tcp.py` | wire-path | fine |\n"
            "| `multiverso_tpu/ghost.py` | wire-path | stale row |\n")
        lint = CopyLint(drifted)
        module = ModuleInfo(FIXTURES / "bad_flags.py", REPO_ROOT)
        found = list(lint.check(module))
        messages = "\n".join(v.message for v in found)
        assert "ghost.py" in messages                 # doc-only row
        assert "core/blob.py" in messages             # missing row
        # both directions fire: 1 stale + 7 missing modules
        assert len(found) == 8

    def test_doc_drift_is_a_violation(self, tmp_path):
        drifted = tmp_path / "WIRE_FORMAT.md"
        drifted.write_text("| 5 | `ERROR_SLOT` |\n"
                           "| 9 | `CODEC_SLOT` |\n"
                           "| 7 | `STALE_SLOT` |\n")
        lint = WireSlotLint({"ERROR_SLOT": 5, "CODEC_SLOT": 6,
                             "VERSION_SLOT": 7}, drifted)
        module = ModuleInfo(FIXTURES / "bad_flags.py", REPO_ROOT)
        findings = [v.message for v in lint.check(module)]
        assert any("drifted from the wire" in m for m in findings)
        assert any("VERSION_SLOT=7 missing" in m for m in findings)
        assert any("stale doc entry" in m for m in findings)


class TestFramework:
    def test_pragma_inside_string_is_inert(self, tmp_path):
        path = tmp_path / "mod.py"
        path.write_text(
            'X = "# mvlint: ignore[flag-lint]"\n'
            'from multiverso_tpu.util.configure import get_flag\n'
            'Y = get_flag("not_a_flag_at_all")\n')
        result = run_passes(build_passes(REPO_ROOT), [str(path)],
                            tmp_path)
        assert any(v.pass_name == "flag-lint"
                   for v in result.violations)

    def test_aliased_lock_is_registered(self, tmp_path):
        # Server._table_lock = device_lock.TABLE_LOCK carries no
        # factory call; the alias must still register or server.py's
        # critical sections go unchecked.
        path = tmp_path / "mod.py"
        path.write_text(
            "from x import device_lock\n"
            "class S:\n"
            "    _table_lock = device_lock.TABLE_LOCK\n"
            "    def bad(self, q):\n"
            "        with self._table_lock:\n"
            "            return q.pop()\n")
        result = run_passes(build_passes(REPO_ROOT), [str(path)],
                            tmp_path)
        assert any(v.pass_name == "lock-discipline"
                   and ".pop" in v.message
                   for v in result.violations), \
            [v.render() for v in result.violations]

    def test_doc_drift_not_suppressible_by_module_pragma(self, tmp_path):
        # Doc findings carry the doc's path; a pragma in whatever file
        # happens to be scanned first must not swallow them.
        drifted = tmp_path / "WIRE_FORMAT.md"
        drifted.write_text("| 9 | `ERROR_SLOT` |\n")
        mod = tmp_path / "first.py"
        mod.write_text("X = 1  # mvlint: ignore[wire-slot]\n")
        lint = WireSlotLint({"ERROR_SLOT": 5}, drifted)
        result = run_passes([lint], [str(mod)], tmp_path)
        assert any("drifted from the wire" in v.message
                   for v in result.violations)
        assert not result.suppressed

    def test_syntax_error_is_reported_not_crashed(self, tmp_path):
        path = tmp_path / "broken.py"
        path.write_text("def oops(:\n")
        result = run_passes(build_passes(REPO_ROOT), [str(path)],
                            tmp_path)
        assert result.failed
        assert result.violations[0].pass_name == "parse"


class TestCli:
    """The acceptance-criterion entry point, end to end."""

    def test_clean_tree_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mvlint",
             "multiverso_tpu", "tests", "bench.py"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "mvlint: OK" in proc.stdout

    def test_fixtures_exit_nonzero_with_file_line(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mvlint",
             "tools/mvlint/fixtures"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        # file:line:col diagnostics
        assert "tools/mvlint/fixtures/bad_flags.py:18:" in proc.stdout
        assert "FAILED" in proc.stderr

    def test_nonexistent_path_is_a_hard_error(self):
        # A drifted path in ci.sh must not let the gate pass vacuously.
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mvlint", "no_such_dir_xyz"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 2
        assert "no_such_dir_xyz" in proc.stderr

    def test_baseline_mode_never_fails(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mvlint", "--baseline",
             "tools/mvlint/fixtures"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0
        assert "violations" in proc.stdout
