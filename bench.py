"""Benchmark entry point for the driver.

Primary metric = the north-star workload: WordEmbedding (skip-gram +
negative sampling) words/sec on one chip through the framework's batched
jitted step (the TPU re-design of the reference's OpenMP word2vec,
ref: Applications/WordEmbedding/src/wordembedding.cpp).

The corpus is synthetic (no network egress in this environment, so enwik9
cannot be fetched): two-topic banded Zipf text at >= 1M raw vocabulary,
which gives the PS path a realistic sparse row working set AND admits a
quality check (within-topic vs cross-topic similarity of frequent words).

Measured and reported honestly (round-2 requirements):
- ``value``: local-mode words/s/chip (must not regress across rounds);
- ``detail.ps_words_per_sec``: the SAME workload trained through the
  parameter-server path — row-sparse pulls, compact jitted step, row
  delta pushes, pipelined (ref: communicator.cpp:117-249);
- ``detail.loss_parity``: fixed-seed loss vs the identical run on the
  host CPU backend, plus the topic-separation quality score;
- ``detail.mfu`` / ``detail.hbm``: achieved FLOP/s and bytes/s for the
  training step against the chip's nominal peaks — the headroom, made
  visible;
- ``detail.matrix_table_bandwidth``: whole-table Add/Get GB/s plus the
  sparse dirty-row Get path (ref: Test/test_matrix_perf.cpp:33-171).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

VOCAB = 1_200_000
SENTENCES = 150_000
WORDS_PER_SENTENCE = 40
EPOCHS = 3
BATCH = 32768
DIM = 128
NEG = 5
PS_MAX_BATCHES = 240  # cap the timed PS segment (words/s is a rate)
MIN_COUNT = 1  # ~1M-word real dictionary on this corpus (reported below)

# Nominal per-chip peaks for utilization reporting (dense matmul peak for
# the compute dtype class; memory bandwidth). Conservative defaults.
_CHIP_PEAKS = {
    # device_kind substring: (flops_peak, hbm_bytes_per_sec)
    "v5 lite": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v4": (275e12, 1228e9),
    "v5p": (459e12, 2765e9),
    "v6": (918e12, 1640e9),
}


def write_corpus(path: str) -> None:
    """Two topic bands over a Zipf(0.8) unigram distribution: sentences
    draw all words from one band, so frequent words cluster by band —
    trainable structure at 1M+ vocabulary scale. The flat exponent (0.8)
    spreads the 6M tokens wide enough that the TRAINED dictionary itself
    exceeds 1M words (reported as vocab_actual), so the PS path is
    exercised at reference-like table heights."""
    rng = np.random.default_rng(0)
    half = VOCAB // 2
    ranks = np.arange(1, half + 1)
    probs = 1.0 / ranks**0.8
    cdf = np.cumsum(probs / probs.sum())
    topics = rng.integers(0, 2, size=SENTENCES)
    draws = rng.random((SENTENCES, WORDS_PER_SENTENCE))
    ids = np.searchsorted(cdf, draws).astype(np.int64)
    ids = np.minimum(ids, half - 1) + topics[:, None] * half
    with open(path, "w") as f:
        for row in ids:
            f.write(" ".join(f"w{i}" for i in row) + "\n")


def _build(corpus: str):
    from multiverso_tpu.models.wordembedding import (Dictionary,
                                                     TokenizedCorpus)
    dictionary = Dictionary.build(corpus, min_count=MIN_COUNT)
    tokenized = TokenizedCorpus.build(dictionary, corpus)
    return dictionary, tokenized


def _timed_batches(gen, walls, words, sync_every=0, sync_fn=None):
    """Record per-batch (or per-window) walls + word counts around a
    batch stream. With ``sync_every``/``sync_fn`` set, batches are
    AGGREGATED into device-synced windows — a fully-async loop's
    per-batch intervals measure host dispatch cadence (overstating the
    rate by orders of magnitude), so each recorded sample must span a
    sync. One entry lands in ``walls``/``words`` per window."""
    last = time.perf_counter()
    acc_words = 0.0
    pending = 0
    for batch in gen:
        yield batch
        if sync_every and sync_fn is not None:
            acc_words += batch.words
            pending += 1
            if pending == sync_every:
                sync_fn()
                now = time.perf_counter()
                walls.append(now - last)
                words.append(acc_words)
                acc_words, pending = 0.0, 0
                last = now
        else:
            now = time.perf_counter()
            walls.append(now - last)
            words.append(batch.words)
            last = now


def run_local(corpus: str, prebuilt=None, epochs: int = EPOCHS,
              schedule_epochs: int = None) -> dict:
    """Train ``epochs`` epochs. ``schedule_epochs`` (default = epochs)
    sets the lr-decay horizon — the CPU parity baseline trains ONE epoch
    under the SAME schedule as the full run, so epoch-0 losses are
    comparable."""
    from multiverso_tpu.models.wordembedding import (BlockLoader,
                                                     Word2Vec,
                                                     Word2VecConfig,
                                                     iter_pair_batches)
    dictionary, tokenized = prebuilt if prebuilt else _build(corpus)
    config = Word2VecConfig(embedding_size=DIM, window=5, negative=NEG,
                            epochs=schedule_epochs or epochs,
                            batch_size=BATCH, sample=1e-3)
    model = Word2Vec(config, dictionary)
    warm = next(iter(iter_pair_batches(dictionary, tokenized,
                                       batch_size=BATCH, window=5,
                                       subsample=1e-3, seed=99)))
    model.train_batch(warm)  # compile outside the timed region
    warm_words = model.trained_words
    epoch_losses = []
    pair_total = 0
    batch_walls = []
    batch_words = []

    def sync():
        import jax
        jax.block_until_ready(model._emb_in)

    start = time.perf_counter()
    for epoch in range(epochs):
        # Row prep runs in the loader thread, overlapped with device
        # steps (model.prepared); the loop only dispatches — so the
        # median timer syncs every 16 batches or it would measure
        # dispatch cadence, not throughput.
        loss_sum, pairs = model.train_batches(_timed_batches(
            BlockLoader(model.prepared(iter_pair_batches(
                dictionary, tokenized, batch_size=BATCH,
                window=5, subsample=1e-3, seed=epoch))),
            batch_walls, batch_words, sync_every=16, sync_fn=sync))
        epoch_losses.append(loss_sum / max(pairs, 1))
        pair_total += pairs
    elapsed = time.perf_counter() - start
    assert all(np.isfinite(x) for x in epoch_losses), epoch_losses
    # Same mean-words-over-median-wall approximation as run_ps: robust
    # to transient transport stalls the wall average folds in.
    med = float(np.median(batch_walls)) if batch_walls else 0.0
    return {
        "wps": (model.trained_words - warm_words) / elapsed,
        "median_batch_wps": round(
            float(np.mean(batch_words)) / med, 0) if med else 0.0,
        "pairs_per_sec": pair_total / elapsed,
        "epoch_losses": [round(float(x), 4) for x in epoch_losses],
        "model": model,
        "dictionary": dictionary,
    }


def run_ps(corpus: str, prebuilt=None) -> dict:
    """Same workload through the parameter-server path (row-sparse
    pulls, compact step, delta pushes, pipelined).

    Single worker by design: N virtual ranks on ONE device measure
    contention, not scaling (each reference worker owns its hardware);
    multi-worker correctness is gated by tests/test_wordembedding.py and
    tests/test_net_integration.py, multi-chip sharding by
    __graft_entry__.dryrun_multichip."""
    import multiverso_tpu as mv
    from multiverso_tpu.models.wordembedding import (BlockLoader,
                                                     PSWord2Vec,
                                                     Word2VecConfig,
                                                     iter_pair_batches)
    dictionary, tokenized = prebuilt if prebuilt else _build(corpus)
    mv.init([])
    config = Word2VecConfig(embedding_size=DIM, window=5, negative=NEG,
                            epochs=1, batch_size=BATCH, sample=1e-3,
                            use_ps=True)
    model = PSWord2Vec(config, dictionary)

    def capped(seed, cap=PS_MAX_BATCHES):
        for i, batch in enumerate(iter_pair_batches(
                dictionary, tokenized, batch_size=BATCH, window=5,
                subsample=1e-3, seed=seed)):
            if i >= cap:
                return
            yield batch

    # Warm OUTSIDE the timed region: 3 serial batches cover the compile
    # set (row gathers per bucket, the fused step, the scatter engine's
    # both post-donation input layouts), then a short PIPELINED stretch
    # brings the loader/actor/device pipeline to steady state — words/s
    # is a rate, and a cold pipeline would understate it.
    for warm_batch in capped(99, cap=3):
        model.train_batch(warm_batch)
    model.train_batches(BlockLoader(model.prepared(capped(98, cap=30))))
    warm_words = model.trained_words
    batch_walls = []
    batch_words = []
    start = time.perf_counter()
    loss_sum, pairs = model.train_batches(_timed_batches(
        BlockLoader(model.prepared(capped(0))),
        batch_walls, batch_words))
    elapsed = time.perf_counter() - start
    words = model.trained_words - warm_words
    # Median per-batch rate: robust to transient transport stalls that
    # the wall-clock average (the headline wps) folds in.
    # Approximation by design: mean(words) over median(wall) — batch
    # sizes are near-constant, and interval i spans batch i's
    # prepare/launch plus batch i-1's finish (pipelined loop).
    med = float(np.median(batch_walls)) if batch_walls else 0.0
    median_wps = (float(np.mean(batch_words)) / med) if med else 0.0
    separation = topic_separation(model.embeddings, dictionary)
    mv.shutdown()
    assert np.isfinite(loss_sum / max(pairs, 1))
    return {"wps": words / elapsed,
            "median_batch_wps": round(float(median_wps), 0),
            "avg_loss": round(loss_sum / max(pairs, 1), 4),
            "separation": round(float(separation), 4)}


def topic_separation(emb: np.ndarray, dictionary) -> float:
    """Within-band minus cross-band cosine similarity of the most
    frequent words of each topic band (quality signal; positive =
    embeddings learned the corpus structure)."""
    half = VOCAB // 2
    per_band = 24
    band_a, band_b = [], []
    for word, wid in dictionary.word2id.items():
        raw = int(word[1:])
        (band_a if raw < half else band_b).append(wid)
        if len(band_a) >= per_band and len(band_b) >= per_band:
            break
    a = emb[band_a[:per_band]]
    b = emb[band_b[:per_band]]
    a = a / np.maximum(np.linalg.norm(a, axis=1, keepdims=True), 1e-9)
    b = b / np.maximum(np.linalg.norm(b, axis=1, keepdims=True), 1e-9)
    within = ((a @ a.T).mean() + (b @ b.T).mean()) / 2
    across = (a @ b.T).mean()
    return within - across


def cpu_baseline(corpus: str) -> dict:
    """Identical fixed-seed run, host CPU backend, separate process."""
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import json, bench\n"
        # Mirror the parent's effective constants so the fixed-seed runs
        # are bit-comparable.
        f"bench.VOCAB={VOCAB}; bench.SENTENCES={SENTENCES}\n"
        f"bench.EPOCHS={EPOCHS}; bench.BATCH={BATCH}\n"
        f"bench.DIM={DIM}; bench.NEG={NEG}\n"
        f"bench.MIN_COUNT={MIN_COUNT}\n"
        # One epoch: words/s is a rate and loss parity compares the
        # fixed-seed FIRST epoch; 3 CPU epochs would triple bench time.
        f"r = bench.run_local({corpus!r}, epochs=1,"
        f" schedule_epochs={EPOCHS})\n"
        "print('RES', json.dumps({'wps': r['wps'],"
        " 'epoch_losses': r['epoch_losses']}))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=os.path.dirname(
        os.path.abspath(__file__)), env=env, capture_output=True,
        text=True, timeout=3000)
    for line in out.stdout.splitlines():
        if line.startswith("RES "):
            return json.loads(line[4:])
    raise RuntimeError(f"cpu baseline failed: {out.stderr[-500:]}")


def utilization(pairs_per_sec: float) -> dict:
    """Achieved FLOP/s and HBM bytes/s for the SGNS step vs chip peaks.

    Per pair (K = NEG negatives, D = DIM): forward logits einsum
    (2*(1+K)*D flops) + two backward einsums (4*(1+K)*D) = 6*(1+K)*D.
    Bytes: input row read+grad r/w (3*D*4) + (1+K) output rows read +
    grad r/w (3*(1+K)*D*4)."""
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "unknown").lower()
    flops_peak, hbm_peak = 197e12, 819e9
    for key, peaks in _CHIP_PEAKS.items():
        if key in kind:
            flops_peak, hbm_peak = peaks
            break
    flops_per_pair = 6 * (1 + NEG) * DIM
    bytes_per_pair = 3 * DIM * 4 + 3 * (1 + NEG) * DIM * 4
    achieved_flops = pairs_per_sec * flops_per_pair
    achieved_bytes = pairs_per_sec * bytes_per_pair
    return {
        "device_kind": kind,
        "achieved_tflops": round(achieved_flops / 1e12, 4),
        "mfu": round(achieved_flops / flops_peak, 6),
        "achieved_gbps": round(achieved_bytes / 1e9, 2),
        "hbm_utilization": round(achieved_bytes / hbm_peak, 4),
    }


def matrix_bandwidth() -> dict:
    import jax.numpy as jnp

    import multiverso_tpu as mv
    from multiverso_tpu.updater import AddOption

    num_row, num_col, iters = 1_000_000, 50, 10
    nbytes = num_row * num_col * 4
    import jax

    mv.init([])
    table = mv.create_matrix_table(num_row, num_col)
    delta = jnp.ones((num_row, num_col), jnp.float32)
    jax.block_until_ready(delta)
    table.add(delta)
    jax.block_until_ready(table.get_device())  # compile + settle
    start = time.perf_counter()
    ids = [table.add_async(delta) for _ in range(iters)]
    for msg_id in ids:
        table.wait(msg_id)
    jax.block_until_ready(table.get_device())
    add_gbps = nbytes / ((time.perf_counter() - start) / (iters + 1)) / 1e9
    start = time.perf_counter()
    outs = [table.get_device() for _ in range(iters)]
    jax.block_until_ready(outs[-1])
    get_gbps = nbytes / ((time.perf_counter() - start) / iters) / 1e9
    del outs

    # Tunnel characterization: the dirty-row sparse Get fills a HOST
    # buffer (reference API semantics), so on a tunneled device it is
    # capped by device->host bandwidth, not by the table stack. Measure
    # and report both directions so the sparse number is interpretable.
    probe = np.ones(4 << 20, np.float32)  # 16 MB
    jax.block_until_ready(jnp.asarray(probe))
    t0 = time.perf_counter()
    dev_probe = jnp.asarray(probe)
    jax.block_until_ready(dev_probe)
    up_mbps = probe.nbytes / (time.perf_counter() - t0) / 1e6
    fresh = jax.block_until_ready(dev_probe * 2.0)
    t0 = time.perf_counter()
    np.asarray(fresh)
    down_mbps = probe.nbytes / (time.perf_counter() - t0) / 1e6
    # Per-call dispatch floor: how long one tiny jitted op takes to
    # dispatch AND complete. On a tunneled device this floor (not
    # compute) often bounds words/s — report it so rates are readable.
    tiny = jax.jit(lambda x: x + 1.0)
    s0 = jax.block_until_ready(tiny(jnp.float32(0)))
    t0 = time.perf_counter()
    for _ in range(20):
        s0 = jax.block_until_ready(tiny(s0))  # block EACH call: the
        # async pipeline would otherwise hide the per-call roundtrip
    dispatch_ms = (time.perf_counter() - t0) / 20 * 1e3

    # Sparse dirty-row path (ref: test_matrix_perf.cpp sparse variants):
    # dirty rows per round, dirty-only whole-table get.
    sparse = mv.create_matrix_table(num_row, num_col, is_sparse=True)
    buf = np.zeros((num_row, num_col), np.float32)
    sparse.get(out=buf)  # initial full sync marks everything clean
    dirty_n = num_row // 50
    rows = np.arange(dirty_n, dtype=np.int32) * 10
    row_delta = np.ones((dirty_n, num_col), np.float32)
    opt = AddOption(worker_id=1)  # dirties the rows for worker 0
    # One untimed roundtrip: compiles the dirty-row gather/scatter for
    # this row-count bucket (compiling inside the timed loop would
    # swamp 3 iterations).
    sparse.add_rows(rows, row_delta, option=opt)
    sparse.get(out=buf)
    start = time.perf_counter()
    sparse_iters = 3
    for _ in range(sparse_iters):
        sparse.add_rows(rows, row_delta, option=opt)
        sparse.get(out=buf)  # returns only the dirty rows
    sparse_elapsed = time.perf_counter() - start
    sparse_bytes = dirty_n * num_col * 4 * 2  # add + dirty-row get
    sparse_gbps = sparse_bytes * sparse_iters / sparse_elapsed / 1e9
    mv.shutdown()
    return {"add_gbps": round(add_gbps, 3),
            "get_gbps": round(get_gbps, 3),
            "sparse_dirty_roundtrip_gbps": round(sparse_gbps, 3),
            "tunnel_upload_mbps": round(up_mbps, 1),
            "tunnel_download_mbps": round(down_mbps, 1),
            "dispatch_roundtrip_ms": round(dispatch_ms, 3)}


def _phase(name: str, fn, *args, **kw):
    """Run one bench phase with stderr progress + timing (stdout carries
    only the final JSON line)."""
    print(f"[bench] {name}...", file=sys.stderr, flush=True)
    start = time.perf_counter()
    out = fn(*args, **kw)
    dt = time.perf_counter() - start
    _phase.seconds[name] = round(dt, 1)
    print(f"[bench] {name} done in {dt:.1f}s", file=sys.stderr, flush=True)
    return out


_phase.seconds = {}


def main() -> None:
    tmp = tempfile.mkdtemp()
    corpus = os.path.join(tmp, "corpus.txt")
    _phase("write_corpus", write_corpus, corpus)
    prebuilt = _phase("build_dictionary", _build, corpus)
    local = _phase("local_train", run_local, corpus, prebuilt)
    ps = _phase("ps_train", run_ps, corpus, prebuilt)
    try:
        cpu = _phase("cpu_baseline", cpu_baseline, corpus)
    except Exception as exc:  # noqa: BLE001 - report without a baseline
        cpu = None
        baseline_err = str(exc)[:200]
    util = utilization(local["pairs_per_sec"])
    matrix = _phase("matrix_bandwidth", matrix_bandwidth)

    parity = None
    if cpu:
        # Fixed-seed epoch-0 comparison (the CPU run does one epoch).
        tpu0, cpu0 = local["epoch_losses"][0], cpu["epoch_losses"][0]
        parity = {
            "tpu_epoch_losses": local["epoch_losses"],
            "cpu_epoch_losses": cpu["epoch_losses"],
            "epoch0_rel_diff": round(
                abs(tpu0 - cpu0) / max(abs(cpu0), 1e-9), 4),
        }
    result = {
        "metric": "wordembedding_words_per_sec_per_chip",
        "value": round(local["wps"], 0),
        "unit": "words/s",
        "vs_baseline": round(local["wps"] / cpu["wps"], 3) if cpu else None,
        "detail": {
            "local_median_batch_words_per_sec": local["median_batch_wps"],
            "ps_words_per_sec": round(ps["wps"], 0),
            "ps_median_batch_words_per_sec": ps["median_batch_wps"],
            "ps_vs_local": round(ps["wps"] / local["wps"], 3),
            "ps_avg_loss": ps["avg_loss"],
            "ps_topic_separation": ps["separation"],
            "loss_parity": parity if parity else baseline_err,
            "mfu": util["mfu"],
            "utilization": util,
            "cpu_backend_words_per_sec": round(cpu["wps"], 0) if cpu
            else None,
            "matrix_table_bandwidth": matrix,
            "phase_seconds": dict(_phase.seconds),
            "setup": {"vocab_raw": VOCAB,
                      "vocab_actual": local["dictionary"].size,
                      "min_count": MIN_COUNT,
                      "sentences": SENTENCES,
                      "epochs": EPOCHS, "batch": BATCH, "dim": DIM,
                      "negative": NEG,
                      "ps_batches": PS_MAX_BATCHES,
                      "corpus": "synthetic 2-topic banded Zipf "
                                "(no egress: enwik9 unavailable)"},
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
