"""Benchmark entry point for the driver.

Mirrors the reference's MatrixTable bandwidth harness
(ref: Test/test_matrix_perf.cpp:33-171: timed whole-table Get/Add of a
1M x 50 fp32 matrix ~= 200 MB) through the full PS stack (worker actor ->
partition -> server -> jit updater), on the TPU-native device-resident
path: deltas and replies are jax.Arrays that stay in HBM end to end, so
the measured bandwidth is the PS overhead + on-device update rate, not a
host-transfer benchmark.

Timing note: on tunneled TPU backends ``block_until_ready`` can return
before execution really finishes, so completion is forced with a scalar
fetch from the result.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` compares against a single-thread numpy element-loop
updater measured on this same host — the stand-in for the reference's
CPU/OpenMP server loop (ref: src/updater/updater.cpp:24-31), since
BASELINE.json carries no published absolute numbers for this harness.
"""

import json
import sys
import time

import numpy as np


def main() -> None:
    num_row, num_col = 1_000_000, 50
    nbytes = num_row * num_col * 4
    iters = 10

    import jax.numpy as jnp

    import multiverso_tpu as mv

    mv.init([])
    table = mv.create_matrix_table(num_row, num_col)
    delta = jnp.ones((num_row, num_col), jnp.float32)
    _ = float(delta[0, 0])  # materialize the delta before timing

    # Warmup: compile update + snapshot programs.
    table.add(delta)
    out = table.get_device()
    _ = float(out[0, 0])

    # Pipelined async adds through the full actor stack; completion forced
    # by fetching a scalar from a final device get.
    start = time.perf_counter()
    ids = [table.add_async(delta) for _ in range(iters)]
    for msg_id in ids:
        table.wait(msg_id)
    out = table.get_device()
    checksum = float(out[0, 0])
    add_s = (time.perf_counter() - start) / (iters + 1)
    add_gbps = nbytes / add_s / 1e9

    start = time.perf_counter()
    for _ in range(iters):
        out = table.get_device()
    checksum += float(out[0, 0])
    get_s = (time.perf_counter() - start) / iters
    get_gbps = nbytes / get_s / 1e9

    value = (add_gbps + get_gbps) / 2

    # Reference stand-in: single-thread numpy element loop + reply copy.
    # One untimed pass first — first-touch page faults would otherwise
    # understate the baseline.
    base_store = np.zeros((num_row, num_col), dtype=np.float32)
    host_delta = np.ones((num_row, num_col), dtype=np.float32)
    host_out = np.empty_like(base_store)
    base_store += host_delta
    np.copyto(host_out, base_store)
    start = time.perf_counter()
    base_store += host_delta
    base_add = nbytes / (time.perf_counter() - start) / 1e9
    start = time.perf_counter()
    np.copyto(host_out, base_store)
    base_get = nbytes / (time.perf_counter() - start) / 1e9
    baseline = (base_add + base_get) / 2

    mv.shutdown()
    print(json.dumps({
        "metric": "matrix_table_add_get_bandwidth",
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / baseline, 3),
        "detail": {
            "add_gbps": round(add_gbps, 3),
            "get_gbps": round(get_gbps, 3),
            "numpy_baseline_gbps": round(baseline, 3),
            "matrix": [num_row, num_col],
            "checksum": checksum,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
