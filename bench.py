"""Benchmark entry point for the driver.

Primary metric = the north-star workload: WordEmbedding (skip-gram +
negative sampling) words/sec on one chip through the framework's batched
jitted step (the TPU re-design of the reference's OpenMP word2vec,
ref: Applications/WordEmbedding/src/wordembedding.cpp).

The corpus is synthetic (no network egress in this environment, so enwik9
cannot be fetched): two-topic banded Zipf text at >= 1M raw vocabulary,
which gives the PS path a realistic sparse row working set AND admits a
quality check (within-topic vs cross-topic similarity of frequent words).

Measured and reported honestly (round-2 requirements):
- ``value``: local-mode words/s/chip (must not regress across rounds);
- ``detail.ps_words_per_sec``: the SAME workload trained through the
  parameter-server path — row-sparse pulls, compact jitted step, row
  delta pushes, pipelined (ref: communicator.cpp:117-249);
- ``detail.loss_parity``: fixed-seed loss vs the identical run on the
  host CPU backend, plus the topic-separation quality score;
- ``detail.mfu`` / ``detail.hbm``: achieved FLOP/s and bytes/s for the
  training step against the chip's nominal peaks — the headroom, made
  visible;
- ``detail.matrix_table_bandwidth``: whole-table Add/Get GB/s plus the
  sparse dirty-row Get path (ref: Test/test_matrix_perf.cpp:33-171).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import contextlib
import glob
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np


@contextlib.contextmanager
def flag_guard():
    """Snapshot/restore EVERY registered flag value around a bench
    phase. Flag state is process-global and survives mv.shutdown()/
    mv.init() cycles, so the old pattern — each phase hand-restoring
    the specific flags it set in a try/finally — has already bitten
    once per the in-file comments (a leaked `max_get_staleness` turns
    the cache on for every later phase's default-flag numbers, a
    leaked `net_pace_mbps` paces every later wire). This guard makes
    the restore structural: whatever `set_flag` calls (or autotune
    Control_Config broadcasts) a phase makes, exit puts every flag
    back — flags registered DURING the phase reset to their defaults."""
    from multiverso_tpu.util.configure import (CANONICAL_FLAGS,
                                               FlagRegister)
    reg = FlagRegister.get()
    before = {name: flag.value for name, flag in reg._flags.items()}
    try:
        yield
    finally:
        for name, flag in reg._flags.items():
            if name in before:
                flag.value = before[name]
            else:
                # Registered DURING the phase. Prefer the canonical
                # default over flag.default: a tunable applied via
                # Control_Config before its defining module imported
                # was implicitly registered with default == the
                # broadcast value, and "restoring" that would leak
                # the tuned knob into every later phase.
                flag.value = CANONICAL_FLAGS.get(name, flag.default)


def flag_guarded(fn):
    """Decorator form of ``flag_guard`` — converts a whole phase: no
    matter how the phase exits, every flag it set is restored."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with flag_guard():
            return fn(*args, **kwargs)
    return wrapper

VOCAB = 1_200_000
SENTENCES = 150_000
WORDS_PER_SENTENCE = 40
EPOCHS = 3
BATCH = 32768
DIM = 128
NEG = 5
PS_MAX_BATCHES = 240  # cap the timed PS segment (words/s is a rate)
MIN_COUNT = 1  # ~1M-word real dictionary on this corpus (reported below)

# Nominal per-chip peaks for utilization reporting (dense matmul peak for
# the compute dtype class; memory bandwidth). Conservative defaults.
_CHIP_PEAKS = {
    # device_kind substring: (flops_peak, hbm_bytes_per_sec)
    "v5 lite": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v4": (275e12, 1228e9),
    "v5p": (459e12, 2765e9),
    "v6": (918e12, 1640e9),
}


def write_corpus(path: str) -> None:
    """Two topic bands over a Zipf(0.8) unigram distribution: sentences
    draw all words from one band, so frequent words cluster by band —
    trainable structure at 1M+ vocabulary scale. The flat exponent (0.8)
    spreads the 6M tokens wide enough that the TRAINED dictionary itself
    exceeds 1M words (reported as vocab_actual), so the PS path is
    exercised at reference-like table heights."""
    rng = np.random.default_rng(0)
    half = VOCAB // 2
    ranks = np.arange(1, half + 1)
    probs = 1.0 / ranks**0.8
    cdf = np.cumsum(probs / probs.sum())
    topics = rng.integers(0, 2, size=SENTENCES)
    draws = rng.random((SENTENCES, WORDS_PER_SENTENCE))
    ids = np.searchsorted(cdf, draws).astype(np.int64)
    ids = np.minimum(ids, half - 1) + topics[:, None] * half
    with open(path, "w") as f:
        for row in ids:
            f.write(" ".join(f"w{i}" for i in row) + "\n")


def _build(corpus: str):
    from multiverso_tpu.models.wordembedding import (Dictionary,
                                                     TokenizedCorpus)
    dictionary = Dictionary.build(corpus, min_count=MIN_COUNT)
    tokenized = TokenizedCorpus.build(dictionary, corpus)
    return dictionary, tokenized


LOCAL_CENTERS = 16384  # centers per device step (window pairs ≈ 2W x C)
LOCAL_DISPATCH = 16    # steps per dispatch group (lax.scan length)
NEG_BLOCK = 8          # fast-mode negative sharing (one K-draw per 8
#   consecutive centers): ~2.4x words/s over per-center draws; the
#   QUALITY record below uses per-pair draws instead.
PS_CENTERS = 32768     # PS blocks pay per-block actor round trips, so
#   bigger blocks win there.
PS_GROUP = 8           # blocks per dispatch in the grouped PS segment
SYNC_GROUPS = 4        # timing-window width, in dispatch groups
# Quality-mode (-per_pair) settings: the sequential-update structure
# that reaches the C++ baseline's topic separation (grid-searched on
# this corpus: C=2048 best; 4-epoch schedule crosses the cpp separation
# at epoch 3 and exceeds it at epoch 4).
QUALITY_C = 2048
QUALITY_DISPATCH = 32
QUALITY_EPOCHS = 4
QUALITY_PS_GROUP = 4   # PS quality mode: 4 blocks per round trip — the
#   largest grouping whose staleness still reaches the cpp separation
#   (G=8 plateaus at ~0.87); 4x fewer per-block program launches makes
#   the crossing time robust to tunnel launch weather
QUALITY_WALL_BUDGET_SEC = 420.0  # wall guard for the quality phases:
#   per-block program launches swing 5-50x with tunnel weather; a
#   bad-weather run reports a partial curve instead of blowing the
#   whole bench's runtime
CPP_SEP_FALLBACK = 1.0305  # r3's measured cpp separation, used only if
#   the cpp phase fails


class _TimedHook:
    """Shared per-hook timing with forced device syncs: every ``every``
    calls, ``sync()`` must force all dispatched work to completion (a
    tiny scalar readback — block_until_ready is not reliable on the
    tunneled platform), and one (wall, words) window sample lands.
    ``median_wps()`` is the steady-state rate estimate."""

    def __init__(self, sync, every: int):
        self._sync = sync
        self._every = every
        self.walls = []
        self.words = []
        self._acc = 0.0
        self._n = 0
        self._t = None

    def start(self) -> None:
        self._t = time.perf_counter()

    def __call__(self, words: float) -> None:
        self._acc += words
        self._n += 1
        if self._n % self._every == 0:
            self._sync()
            now = time.perf_counter()
            self.walls.append(now - self._t)
            self.words.append(self._acc)
            self._t = now
            self._acc = 0.0

    def median_wps(self) -> float:
        med = float(np.median(self.walls)) if self.walls else 0.0
        return (float(np.mean(self.words)) / med) if med else 0.0


def run_local(corpus: str, prebuilt=None, epochs: int = EPOCHS,
              schedule_epochs: int = None, warm: bool = True) -> dict:
    """Train ``epochs`` epochs through the device-resident pipeline
    (corpus in HBM; in-jit subsample/window/negatives — see
    models/wordembedding/device_train.py). ``schedule_epochs``
    (default = epochs) sets the lr-decay horizon — the CPU parity twin
    trains ONE epoch under the SAME schedule, so epoch-0 losses are
    comparable. ``warm=True`` compiles on a throwaway model first (the
    jitted group program is shared via the module-level cache), keeping
    XLA compilation out of the timed region."""
    from multiverso_tpu.models.wordembedding import (DeviceCorpusTrainer,
                                                     Word2Vec,
                                                     Word2VecConfig)
    dictionary, tokenized = prebuilt if prebuilt else _build(corpus)

    def make_model():
        config = Word2VecConfig(embedding_size=DIM, window=5,
                                negative=NEG,
                                epochs=schedule_epochs or epochs,
                                batch_size=BATCH, sample=1e-3,
                                neg_block=NEG_BLOCK)
        return Word2Vec(config, dictionary)

    if warm:
        warm_model = make_model()
        # TWO group calls: the first runs on freshly-uploaded (host
        # layout) tables, the second feeds back donated XLA-layout
        # outputs — each is its own compiled variant, and both must be
        # warm or epoch 0 eats a second compile mid-timing.
        DeviceCorpusTrainer(warm_model, tokenized, LOCAL_CENTERS,
                            LOCAL_DISPATCH).train_epoch(
            seed=99, max_steps=2 * LOCAL_DISPATCH)
        float(warm_model._emb_in[0, 0])  # compile the sync read too
        del warm_model

    model = make_model()
    trainer = DeviceCorpusTrainer(model, tokenized, LOCAL_CENTERS,
                                  LOCAL_DISPATCH)
    # Force the embedding init and corpus upload to COMPLETE before the
    # clock starts (dispatch is async; the transfers would otherwise
    # land inside the first timed window).
    float(model._emb_in[0, 0])
    float(trainer._corpus.flat[0])
    hook = _TimedHook(lambda: float(model._emb_in[0, 0]), SYNC_GROUPS)
    epoch_losses = []
    pair_total = 0.0
    start = time.perf_counter()
    hook.start()
    for epoch in range(epochs):
        loss_sum, pairs = trainer.train_epoch(seed=epoch, group_hook=hook)
        epoch_losses.append(loss_sum / max(pairs, 1))
        pair_total += pairs
    elapsed = time.perf_counter() - start
    assert all(np.isfinite(x) for x in epoch_losses), epoch_losses
    return {
        "wps": model.trained_words / elapsed,
        "median_batch_wps": round(hook.median_wps(), 0),
        "pairs_per_sec": pair_total / elapsed,
        "centers_per_sec": trainer.kept_words_trained / elapsed,
        # One program launch per dispatch group (= one group_hook call):
        # feeds the launch-overhead share of the time decomposition.
        "groups_per_sec": hook._n / elapsed,
        "epoch_losses": [round(float(x), 4) for x in epoch_losses],
        "model": model,
        "dictionary": dictionary,
    }


def run_ps(corpus: str, prebuilt=None) -> dict:
    """Same workload through the parameter-server path: the HBM corpus
    pipeline driving PS matrix tables with DEVICE-RESIDENT keys — every
    block's pull/train/push crosses the full worker/server actor stack
    (models/wordembedding/device_train.py PSDeviceCorpusTrainer). A
    short host-batch PS segment (the cross-process-capable path) is
    timed alongside for continuity with earlier rounds.

    Single worker by design: N virtual ranks on ONE device measure
    contention, not scaling (each reference worker owns its hardware);
    multi-worker correctness is gated by tests/test_wordembedding.py and
    tests/test_net_integration.py, multi-chip sharding by
    __graft_entry__.dryrun_multichip."""
    import multiverso_tpu as mv
    from multiverso_tpu.models.wordembedding import (PSDeviceCorpusTrainer,
                                                     PSWord2Vec,
                                                     Word2VecConfig)
    dictionary, tokenized = prebuilt if prebuilt else _build(corpus)
    mv.init([])
    config = Word2VecConfig(embedding_size=DIM, window=5, negative=NEG,
                            epochs=EPOCHS, batch_size=BATCH, sample=1e-3,
                            use_ps=True, neg_block=NEG_BLOCK)
    model = PSWord2Vec(config, dictionary)
    trainer = PSDeviceCorpusTrainer(model, tokenized, PS_CENTERS)

    # Warm OUTSIDE the timed region (compiles: block-id program, table
    # gathers, the step, the server scatter engines incl. both donated
    # layout variants). The COLD rate (compile included) is reported
    # alongside.
    cold_start = time.perf_counter()
    trainer.train_epoch(seed=99, max_steps=4)
    warm_secs = time.perf_counter() - cold_start
    warm_words = model.trained_words

    # PS blocks are single steps (no scan), so the same wall-clock
    # window width = SYNC_GROUPS * LOCAL_DISPATCH blocks.
    hook = _TimedHook(lambda: float(trainer.last_loss),
                      SYNC_GROUPS * LOCAL_DISPATCH)
    start = time.perf_counter()
    hook.start()
    loss_sum = 0.0
    pairs = 0.0
    for epoch in range(EPOCHS):
        ep_loss, ep_pairs = trainer.train_epoch(seed=epoch,
                                                block_hook=hook)
        loss_sum += ep_loss
        pairs += ep_pairs
    elapsed = time.perf_counter() - start
    words = model.trained_words - warm_words
    median_wps = hook.median_wps()

    # Grouped-dispatch segment: G blocks per pull/step/push round trip
    # (blocks_per_dispatch — bounded staleness, the reference's
    # sync_frequency trade) amortizes the per-block program launches
    # that bound the per-block PS path on the tunneled chip.
    grouped = PSDeviceCorpusTrainer(model, tokenized, PS_CENTERS,
                                    blocks_per_dispatch=PS_GROUP)
    grouped.train_epoch(seed=96, max_steps=2 * PS_GROUP)  # warm
    g_words0 = model.trained_words
    g_start = time.perf_counter()
    grouped.train_epoch(seed=95, max_steps=PS_GROUP * 16)
    float(grouped.last_loss)
    grouped_wps = (model.trained_words - g_words0) \
        / (time.perf_counter() - g_start)

    # Observability artifacts for the overhead hunt: the Dashboard
    # counter report (stderr) and an xprof trace of a few PS blocks
    # (ref: the reference ends its perf harness with Dashboard::Display,
    # Test/test_matrix_perf.cpp:125).
    from multiverso_tpu.util.dashboard import Dashboard, trace_to
    trace_dir = os.path.join(tempfile.gettempdir(), "mv_ps_xprof")
    try:
        with trace_to(trace_dir):
            trainer.train_epoch(seed=97, max_steps=4)
    except Exception as exc:  # noqa: BLE001 - tracing is best-effort
        trace_dir = f"unavailable: {exc}"
    dashboard = Dashboard.display()
    print(f"[bench] PS dashboard:\n{dashboard}", file=sys.stderr)
    print(f"[bench] PS xprof trace: {trace_dir}", file=sys.stderr)
    model._drain_pushes()
    separation = topic_separation(
        None, dictionary,
        fetch_rows=lambda ids: model._in_table.get_rows(ids))
    mv.shutdown()
    assert np.isfinite(loss_sum / max(pairs, 1))
    return {"wps": words / elapsed,
            "grouped_wps": round(grouped_wps, 0),
            "dashboard": dashboard.splitlines(),
            "xprof_trace_dir": trace_dir,
            "cold_wps": round(
                (words + warm_words) / (warm_secs + elapsed), 0),
            "warmup_seconds": round(warm_secs, 1),
            "median_batch_wps": round(float(median_wps), 0),
            "avg_loss": round(loss_sum / max(pairs, 1), 4),
            "separation": round(float(separation), 4)}


def run_hs(prebuilt) -> dict:
    """Hierarchical softmax on the local device pipeline (banded
    Huffman paths — one path gather per band position): a capped
    timed segment reporting HS words/s (VERDICT r3 #5)."""
    from multiverso_tpu.models.wordembedding import (DeviceCorpusTrainer,
                                                     Word2Vec,
                                                     Word2VecConfig)
    dictionary, tokenized = prebuilt
    config = Word2VecConfig(embedding_size=DIM, window=5, negative=0,
                            hs=True, epochs=EPOCHS, sample=1e-3)
    # Same warm-then-time protocol as run_local (throwaway model warms
    # both donated-layout variants; drop it BEFORE the timed model so
    # two sets of tables + corpus never coexist in HBM; sync the corpus
    # upload or it lands inside the timed window).
    warm_model = Word2Vec(config, dictionary)
    DeviceCorpusTrainer(warm_model, tokenized, centers_per_step=8192,
                        steps_per_dispatch=8).train_epoch(
        seed=99, max_steps=16)
    float(warm_model._emb_in[0, 0])
    del warm_model
    model = Word2Vec(config, dictionary)
    trainer = DeviceCorpusTrainer(model, tokenized,
                                  centers_per_step=8192,
                                  steps_per_dispatch=8)
    float(model._emb_in[0, 0])
    float(trainer._corpus.flat[0])
    start = time.perf_counter()
    loss, pairs = trainer.train_epoch(seed=0, max_steps=96)
    float(model._emb_in[0, 0])
    elapsed = time.perf_counter() - start
    return {"wps": round(model.trained_words / elapsed, 0),
            "avg_loss": round(loss / max(pairs, 1), 4),
            "centers_per_step": trainer._C,
            "path_len": int(model._points_host.shape[1])}


HOSTBATCH_SIZE = 131072  # the host-batch path is upload/dispatch bound
#   per BLOCK, so the cross-process-capable segment uses reference-style
#   big data blocks (the reference's loader also ships multi-sentence
#   blocks, ref: distributed_wordembedding.cpp:33-56)


def run_hostbatch(prebuilt) -> dict:
    """The HOST-BATCH PS path (row sets prepped host-side — the form
    that also runs cross-process over TCP), timed as its own phase with
    reference-style large blocks."""
    import multiverso_tpu as mv
    from multiverso_tpu.models.wordembedding import (BlockLoader,
                                                     PSWord2Vec,
                                                     Word2VecConfig,
                                                     iter_pair_batches)
    dictionary, tokenized = prebuilt
    mv.init([])
    config = Word2VecConfig(embedding_size=DIM, window=5, negative=NEG,
                            epochs=EPOCHS, batch_size=HOSTBATCH_SIZE,
                            sample=1e-3, use_ps=True,
                            neg_block=NEG_BLOCK)
    model = PSWord2Vec(config, dictionary)

    def capped(seed, cap):
        for i, batch in enumerate(iter_pair_batches(
                dictionary, tokenized, batch_size=HOSTBATCH_SIZE,
                window=5, subsample=1e-3, seed=seed)):
            if i >= cap:
                return
            yield batch

    for warm_batch in capped(99, 3):
        model.train_batch(warm_batch)
    # Bring the loader/actor/device pipeline to steady state before
    # timing — words/s is a rate, and a cold pipeline understates it.
    model.train_batches(BlockLoader(model.prepared(capped(98, 6))))
    words_0 = model.trained_words
    start = time.perf_counter()
    model.train_batches(BlockLoader(model.prepared(capped(0, 72))))
    model._drain_pushes()
    elapsed = time.perf_counter() - start
    mv.shutdown()
    return {"wps": round((model.trained_words - words_0) / elapsed, 0),
            "batch_size": HOSTBATCH_SIZE}


def run_quality(prebuilt, cpp_sep: float, use_ps: bool) -> dict:
    """TIME-TO-QUALITY record: train the -per_pair quality mode (per-
    pair negatives + sequential window sub-steps — the reference's
    update structure, models/wordembedding/device_train.py
    _seq_pair_step) until topic separation reaches the C++ baseline's
    3-epoch value, and report the wall-clock. This is the honest half
    of the throughput claim: the fast banded mode above measures raw
    words/s; this measures learning the same structure the sequential
    C++ SGD learns, in less time."""
    import jax.numpy as jnp

    import multiverso_tpu as mv
    from multiverso_tpu.models.wordembedding import (
        DeviceCorpusTrainer, PSDeviceCorpusTrainer, PSWord2Vec, Word2Vec,
        Word2VecConfig)
    dictionary, tokenized = prebuilt
    config = Word2VecConfig(embedding_size=DIM, window=5, negative=NEG,
                            epochs=QUALITY_EPOCHS, sample=1e-3,
                            per_pair=True, use_ps=use_ps)

    def setup():
        """(model, trainer, fetch) — one shared construction for the
        warm pass and the timed pass, so they cannot drift apart."""
        if use_ps:
            mv.init([])
            model = PSWord2Vec(config, dictionary)
            trainer = PSDeviceCorpusTrainer(
                model, tokenized, QUALITY_C,
                blocks_per_dispatch=QUALITY_PS_GROUP)

            def fetch(ids):
                model._drain_pushes()
                return model._in_table.get_rows(ids)
        else:
            model = Word2Vec(config, dictionary)
            trainer = DeviceCorpusTrainer(model, tokenized, QUALITY_C,
                                          QUALITY_DISPATCH)

            def fetch(ids):
                return np.asarray(model._emb_in[jnp.asarray(ids)])

        return model, trainer, fetch

    # Warm the compile set out of the timed region (cached across runs).
    model, trainer, fetch = setup()
    trainer.train_epoch(seed=99, max_steps=2 * QUALITY_DISPATCH)
    fetch(np.array([0], np.int32))
    if use_ps:
        mv.shutdown()
    model, trainer, fetch = setup()
    if not use_ps:
        float(model._emb_in[0, 0])

    start = time.perf_counter()
    # The phase's own wall guard must also fit inside the GLOBAL bench
    # budget: the phase-skip estimate assumes a typical run, and bad
    # launch weather may legitimately push the phase to its cap — cap
    # it at what the global budget has left (less a teardown margin).
    global_left = (_BENCH_T0 + WALL_BUDGET_SEC) - time.monotonic() - 30.0
    deadline = start + max(min(QUALITY_WALL_BUDGET_SEC, global_left),
                           10.0)

    class _Deadline(Exception):
        pass

    def deadline_hook(words):
        # Checked per dispatch group, so a single bad-weather epoch
        # cannot blow the budget many times over before the first
        # epoch-boundary check.
        if time.perf_counter() > deadline:
            raise _Deadline

    hook_kw = {"block_hook" if use_ps else "group_hook": deadline_hook}
    curve = []
    losses = []
    time_to_quality = None
    guard_tripped = False
    for epoch in range(QUALITY_EPOCHS):
        try:
            loss, pairs = trainer.train_epoch(seed=epoch, **hook_kw)
        except _Deadline:
            guard_tripped = True
            if use_ps:
                # The aborted epoch left async pushes in flight; wait
                # their acks so shutdown does not race the actors.
                model._drain_pushes()
            break
        losses.append(round(loss / max(pairs, 1), 4))
        sep = float(topic_separation(None, dictionary, fetch_rows=fetch))
        elapsed = time.perf_counter() - start
        curve.append({"epoch": epoch, "separation": round(sep, 4),
                      "elapsed_sec": round(elapsed, 1)})
        if sep >= cpp_sep and time_to_quality is None:
            time_to_quality = round(elapsed, 1)
            break  # record set; spend no more bench time here
        if time.perf_counter() > deadline:
            guard_tripped = True
            break
    if use_ps:
        mv.shutdown()
    return {"time_to_cpp_quality_sec": time_to_quality,
            "cpp_separation_target": round(cpp_sep, 4),
            "wall_guard_tripped": guard_tripped,
            "curve": curve, "epoch_losses": losses,
            "mode": "ps" if use_ps else "local"}


def run_ps_two_workers(prebuilt, blocks: int = 48) -> dict:
    """A MEASURED 2-worker/1-server number (VERDICT r3 #7): two virtual
    worker ranks drive concurrent device-key streams through one shared
    server on one chip — aggregate words/s quantifies server-side
    serialization of concurrent workers (not chip scaling; each
    reference worker owns its hardware)."""
    from multiverso_tpu.models.wordembedding import (PSDeviceCorpusTrainer,
                                                     PSWord2Vec,
                                                     Word2VecConfig)
    from multiverso_tpu.runtime.cluster import LocalCluster
    dictionary, tokenized = prebuilt

    def body(rank):
        import multiverso_tpu as mv
        config = Word2VecConfig(embedding_size=DIM, window=5,
                                negative=NEG, epochs=EPOCHS,
                                batch_size=BATCH, sample=1e-3,
                                use_ps=True, neg_block=NEG_BLOCK)
        model = PSWord2Vec(config, dictionary)
        trainer = PSDeviceCorpusTrainer(model, tokenized, PS_CENTERS)
        trainer.train_epoch(seed=99, max_steps=2)  # warm
        mv.current_zoo().barrier()
        w0 = model.trained_words
        t0 = time.perf_counter()
        trainer.train_epoch(seed=rank, max_steps=blocks)
        elapsed = time.perf_counter() - t0
        return model.trained_words - w0, elapsed

    cluster = LocalCluster(2, roles=["all", "worker"])
    cluster.timeout = 600.0  # 2 ranks time-share one dispatch path
    results = cluster.run(body)
    words = sum(r[0] for r in results)
    elapsed = max(r[1] for r in results)
    return {"aggregate_wps": round(words / elapsed, 0),
            "per_worker": [round(r[0] / r[1], 0) for r in results]}


_SHARD_CHILD = r"""
import os, sys, time, json
import faulthandler
faulthandler.dump_traceback_later(240 + 60 * int(sys.argv[2]), exit=True)
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_compilation_cache_dir',
                  os.path.join({repo!r}, '.jax_cache'))
jax.config.update('jax_persistent_cache_min_compile_time_secs', 5)
sys.path.insert(0, {repo!r})
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.runtime import actor as actors
from multiverso_tpu.util.dashboard import Dashboard, samples

rank = int(sys.argv[1]); n = int(sys.argv[2])
n_servers = n - 1
# Rank 0 is the controller + THE worker; every other rank hosts one
# server shard, so each server owns its own (emulated) wire.
role = 'worker' if rank == 0 else 'server'
mv.init(['-machine_file=' + {mf!r}, '-rank=' + str(rank),
         '-ps_role=' + role, '-net_pace_mbps={pace}',
         '-replica_hot_rows={hot_rows}', '-replica_report_gets=16',
         '-replica_min_gets={min_gets}', '-replica_sync_every={sync_every}',
         '-replica_sync_rows=8'])
ROWS, COLS = {rows}, {cols}
# A POOL of tables, as in a real model (word2vec alone has input +
# output embeddings): the measured loop round-robins async Gets across
# the pool, so per-op fixed costs (partition, turnaround, scheduler
# latency on this one-core box) pipeline behind the paced wire instead
# of adding to every op's critical path — each table still honors the
# one-Get-in-flight rule.
POOL = {pool}
tables = [mv.create_matrix_table(ROWS, COLS)  # creation barrier inside
          for _ in range(POOL)]
table = tables[0]
rng = np.random.default_rng(1234 + rank)


def zipf_ids(k):
    # Word2vec-shaped key stream: ids sorted by frequency, so the Zipf
    # head is CLUSTERED at low ids — i.e. inside server 0's row range.
    # That concentration is exactly what hot-shard replication exists
    # to fix (docs/SHARDING.md).
    return np.unique((rng.zipf({zipf_a}, k) - 1) % ROWS).astype(np.int32)


if rank == 0:
    table.add(rng.standard_normal((ROWS, COLS)).astype(np.float32))
    mv.barrier()  # content line
    # Bucket-size warm sweep: per-shard gather jits compile per padded
    # bucket width — a first-seen width MID-WINDOW is a multi-hundred-ms
    # compile stall charged to one unlucky get.
    for k in (4, 8, 16, 24, 32, 48, 64, 96, 128, 192, 256):
        for t in tables:
            t.get_rows(np.linspace(0, ROWS - 1, k).astype(np.int32))
    t_end = time.perf_counter() + {warm_s}
    t_cap = time.perf_counter() + 4 * {warm_s}
    expect_replica = n_servers > 1 and {hot_rows} > 0
    while time.perf_counter() < t_end or (
            expect_replica and time.perf_counter() < t_cap
            and not (table._replica_router is not None
                     and table._replica_router.active)):
        # Warm jits AND drive hot-row promotion: the timed window
        # must measure the steady replicated state, not the
        # promotion ramp (the cap keeps a broken control plane
        # from wedging the phase; the result will show rate=None).
        for t in tables:
            t.get_rows(zipf_ids({draws}))
    mv.barrier()  # start line
    lat = []
    rows_got = ops = adds = 0
    inflight = []  # (table, msg_id, n_rows, issued_at) oldest first
    t0 = time.perf_counter()
    t_end = t0 + {window_s}
    slot = 0
    while time.perf_counter() < t_end:
        ids = zipf_ids({draws})
        t = tables[slot % POOL]
        slot += 1
        inflight.append((t, t.get_rows_async(ids), ids.size,
                         time.perf_counter()))
        if len(inflight) < POOL:
            continue
        t, mid, n_rows, issued = inflight.pop(0)
        t.wait(mid)
        lat.append((time.perf_counter() - issued) * 1e3)
        rows_got += n_rows
        ops += 1
        if ops % {add_every} == 0:  # write-through + RYW floors exercised
            aid = zipf_ids({add_draws})
            table.add_rows(aid,
                           np.full((aid.size, COLS), 1e-3, np.float32))
            adds += 1
    for t, mid, n_rows, issued in inflight:
        t.wait(mid)
        rows_got += n_rows
        ops += 1
    elapsed = time.perf_counter() - t0
    mv.barrier()  # exit line
    worker = mv.current_zoo()._actors.get(actors.WORKER)
    comm = mv.current_zoo()._actors.get(actors.COMMUNICATOR)
    lat.sort()
    pick = lambda p: round(lat[min(int(len(lat) * p / 100),
                                   len(lat) - 1)], 3) if lat else None
    out = {{'rank': rank, 'get_ops': ops, 'adds': adds,
            'elapsed': round(elapsed, 3),
            'rows_per_s': round(rows_got / elapsed, 1),
            'get_p50_ms': pick(50), 'get_p99_ms': pick(99),
            'reqs_by_dst': {{str(k): v for k, v
                             in worker.request_counts().items()}},
            'queue_depths': {{str(k): v for k, v
                              in comm.queue_depths().items()}},
            'dispatch_ms': {{str(d): samples('DISPATCH_MS[d{{}}]'
                                             .format(d)).snapshot()
                             for d in range(1, n)}},
            'repairs': Dashboard.get('REPLICA_REPAIR').count,
            'stale_groups': Dashboard.get('REPLICA_STALE').count}}
else:
    for _ in range(3):  # content / start / exit lines
        mv.barrier()
    out = {{'rank': rank,
            'server_gets': Dashboard.get('SERVER_PROCESS_GET').count,
            'replica_hit_rows': Dashboard.get('REPLICA_HIT').count,
            'replica_miss_rows': Dashboard.get('REPLICA_MISS').count,
            'replica_syncs': Dashboard.get('REPLICA_SYNC').count}}
faulthandler.cancel_dump_traceback_later()
print('SHARDRES', json.dumps(out), flush=True)
mv.barrier()
mv.shutdown()
"""


def _run_shard_point(tmp: str, n_servers: int, pace_mbps: float,
                     hot_rows: int, rows: int, cols: int,
                     zipf_a: float, draws: int, warm_s: float,
                     window_s: float, min_gets: int = 2,
                     sync_every: int = 8, add_every: int = 32,
                     add_draws: int = 8, pool: int = 4) -> dict:
    """One point of the N-server scale-out sweep: 1 worker + n_servers
    server processes on a paced localhost TCP mesh."""
    from multiverso_tpu.util.net_util import free_listen_port
    n = n_servers + 1
    mf = os.path.join(tmp, f"shard_mf_{n_servers}.txt")
    with open(mf, "w") as f:
        for p in [free_listen_port() for _ in range(n)]:
            f.write(f"127.0.0.1:{p}\n")
    code = _SHARD_CHILD.format(
        repo=os.path.dirname(os.path.abspath(__file__)), mf=mf,
        pace=pace_mbps, hot_rows=hot_rows, rows=rows, cols=cols,
        zipf_a=zipf_a, draws=draws, warm_s=warm_s, window_s=window_s,
        min_gets=min_gets, sync_every=sync_every, add_every=add_every,
        add_draws=add_draws, pool=pool)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", code, str(rank), str(n)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for rank in range(n)]
    results = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            if p.returncode:
                raise RuntimeError(f"shard child failed: {err[-300:]}")
            for line in out.splitlines():
                if line.startswith("SHARDRES "):
                    results.append(json.loads(line[9:]))
    finally:
        for p in procs:  # a raise must not orphan sibling ranks
            if p.poll() is None:
                p.kill()
                p.communicate()
    worker = next(r for r in results if r["rank"] == 0)
    servers = sorted((r for r in results if r["rank"] != 0),
                     key=lambda r: r["rank"])
    hits = sum(s["replica_hit_rows"] for s in servers)
    misses = sum(s["replica_miss_rows"] for s in servers)
    return {
        "n_servers": n_servers,
        "rows_per_s": worker["rows_per_s"],
        "get_p50_ms": worker["get_p50_ms"],
        "get_p99_ms": worker["get_p99_ms"],
        "get_ops": worker["get_ops"],
        "reqs_by_dst": worker["reqs_by_dst"],
        "dispatch_ms": worker["dispatch_ms"],
        "queue_depths": worker["queue_depths"],
        "repairs": worker["repairs"],
        "stale_groups": worker["stale_groups"],
        "per_server_gets": [s["server_gets"] for s in servers],
        "replica_hit_rows": hits,
        "replica_miss_rows": misses,
        "replica_hit_rate": round(hits / (hits + misses), 3)
        if hits + misses else None,
        "replica_syncs": sum(s["replica_syncs"] for s in servers),
    }


def run_ps_two_servers(prebuilt=None, tmp: str = None,
                       servers=(1, 2, 4)) -> dict:
    """N-server scale-out sweep (ISSUE 7 tentpole proof): 1 worker
    driving Zipf-skewed row Get/Add traffic against N in {1,2,4} server
    processes over the paced TCP transport (-net_pace_mbps emulates one
    DCN-speed link PER endpoint, so N servers = N independent wires —
    the deployment the sharded design is for; this box's single core
    cannot show device-side scaling). The old one-chip device-pipeline
    comparison this phase replaces measured broadcast physics (each
    server processed the full key set on ONE chip — 2 servers were 2x
    the device work) and could never reach 1.0x; docs/SHARDING.md
    records the analysis. The Zipf head is CLUSTERED in server 0's row
    range, as in word2vec's frequency-sorted vocabulary: without
    hot-shard replication the head's bytes all leave server 0's wire
    and siblings idle; with it (-replica_hot_rows) the head stripes
    across every server's wire. Reports per-server request counts,
    per-destination dispatch p50/p99 + queue depths, and the replica
    hit rate, so a future regression localizes itself from the bench
    record alone."""
    if tmp is None:
        tmp = tempfile.mkdtemp(prefix="mv_shard_")
    sweep = []
    for n_servers in servers:
        sweep.append(_run_shard_point(
            tmp, n_servers, pace_mbps=8.0, hot_rows=256,
            rows=4096, cols=512, zipf_a=1.6, draws=512,
            warm_s=4.0, window_s=6.0, min_gets=3, sync_every=4,
            add_every=64, pool=2))
    by_n = {point["n_servers"]: point for point in sweep}
    base = by_n.get(1, {}).get("rows_per_s")
    ratios = {n: round(point["rows_per_s"] / base, 3)
              for n, point in by_n.items()} if base else {}
    monotonic = all(
        by_n[a]["rows_per_s"] < by_n[b]["rows_per_s"]
        for a, b in zip(sorted(by_n), sorted(by_n)[1:]))
    return {"sweep": sweep,
            "scaling_vs_one_server": ratios,
            "monotonic_1_2_4": monotonic,
            "vs_single_same_window": ratios.get(2),
            "pace_mbps": 8.0, "replica_hot_rows": 256}


_ELASTIC_CHILD = r"""
import os, sys, time, json
import faulthandler
faulthandler.dump_traceback_later(360, exit=True)
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, {repo!r})
import numpy as np
import multiverso_tpu as mv
rank = int(sys.argv[1]); n = int(sys.argv[2])
role = 'worker' if rank == 0 else 'server'
mv.init(['-machine_file=' + {mf!r}, '-rank=' + str(rank),
         '-ps_role=' + role, '-net_pace_mbps={pace}',
         '-shard_initial_servers=2', '-reshard_chunk_rows=256',
         '-heartbeat_interval_s=0.5', '-heartbeat_timeout_s=5',
         '-rpc_retry_max=8', '-rpc_backoff_ms=50'])
table = mv.create_matrix_table({rows}, {cols})
if rank != 0:
    # Servers idle until the worker's goodbye barrier.
    mv.barrier()
    mv.shutdown()
    sys.exit(0)
rng = np.random.default_rng(7)
expect = rng.standard_normal(({rows}, {cols})).astype(np.float32)
table.add(expect.copy())
shadow = expect


def window(label, seconds, reshard_to=None):
    '''Drive row Gets (verified element-wise) for a timed window;
    reshard_to fires MID-window so the transition itself is measured
    inside the window it claims to improve.'''
    t0 = time.perf_counter()
    rows_served = 0
    failed = wrong = 0
    resharded = reshard_to is None
    add_tick = 0
    while time.perf_counter() - t0 < seconds:
        if not resharded and time.perf_counter() - t0 > 1.0:
            resharded = True
            mv.current_zoo().reshard_table(table, reshard_to,
                                           wait_s=0)
        ids = np.sort(rng.choice({rows}, size={get_rows},
                                 replace=False)).astype(np.int32)
        try:
            got = table.get_rows(ids)
            if not np.allclose(got, shadow[ids], atol=1e-5):
                wrong += 1
            rows_served += ids.size
        except Exception:
            failed += 1
        add_tick += 1
        if add_tick % 16 == 0:
            # A few writes keep the dual-write window honest.
            aid = np.sort(rng.choice({rows}, size=8,
                                     replace=False)).astype(np.int32)
            d = np.ones((8, {cols}), np.float32) * 0.001
            try:
                table.add_rows(aid, d)
                shadow[aid] += d
            except Exception:
                failed += 1
    dt = time.perf_counter() - t0
    return dict(label=label, rows_per_s=round(rows_served / dt, 1),
                failed=failed, wrong=wrong,
                owners=table.shard_owner_sids(),
                epoch=table.shard_epoch())


out = []
out.append(window('w1_two_servers', {window_s}))
out.append(window('w2_grown', {window_s} + 8.0,
                  reshard_to=[0, 1, 2]))
out.append(window('w3_grown_steady', {window_s}))
out.append(window('w4_drained', {window_s} + 8.0,
                  reshard_to=[0, 1]))
faulthandler.cancel_dump_traceback_later()
print('ELASTICRES', json.dumps(out), flush=True)
mv.barrier()
mv.shutdown()
"""


def run_elastic(tmp: str = None) -> dict:
    """Elastic-resharding phase (ISSUE 12 acceptance,
    docs/SHARDING.md): 1 pure worker + 3 server processes on a paced
    localhost TCP mesh (8 Mbps per endpoint — each server owns its
    emulated DCN link). The table starts on 2 servers
    (-shard_initial_servers=2, server 2 a standby); mid-run the worker
    grows it onto all three with LIVE row migration and later drains
    back — while every read is verified element-wise against a shadow
    model. Acceptance: the grown steady-state moves more rows/s than
    the 2-server window (one extra paced link's worth), the drain
    converges back, and ZERO requests fail or return wrong values
    across both transitions."""
    if tmp is None:
        tmp = tempfile.mkdtemp(prefix="mv_elastic_")
    from multiverso_tpu.util.net_util import free_listen_port
    n = 4
    mf = os.path.join(tmp, "elastic_mf.txt")
    with open(mf, "w") as f:
        for p in [free_listen_port() for _ in range(n)]:
            f.write(f"127.0.0.1:{p}\n")
    code = _ELASTIC_CHILD.format(
        repo=os.path.dirname(os.path.abspath(__file__)), mf=mf,
        pace=8.0, rows=1024, cols=256, get_rows=64, window_s=6.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", code, str(rank), str(n)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for rank in range(n)]
    windows = None
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            if p.returncode:
                raise RuntimeError(
                    f"elastic child failed: {err[-400:]}")
            for line in out.splitlines():
                if line.startswith("ELASTICRES "):
                    windows = json.loads(line[11:])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    if windows is None:
        raise RuntimeError("elastic worker never reported")
    by = {w["label"]: w for w in windows}
    failed = sum(w["failed"] for w in windows)
    wrong = sum(w["wrong"] for w in windows)
    grow_ratio = round(by["w3_grown_steady"]["rows_per_s"]
                       / max(by["w1_two_servers"]["rows_per_s"], 1e-9),
                       3)
    drain_ratio = round(by["w4_drained"]["rows_per_s"]
                        / max(by["w1_two_servers"]["rows_per_s"],
                              1e-9), 3)
    return {
        "windows": windows,
        "failed_requests": failed,
        "wrong_values": wrong,
        "grown_vs_two_servers": grow_ratio,
        "drained_vs_two_servers": drain_ratio,
        "grown_owner_sids": by["w3_grown_steady"]["owners"],
        "drained_owner_sids": by["w4_drained"]["owners"],
        # Acceptance: more links = more rows/s, zero failed/wrong
        # requests across both live transitions.
        "accept_grow_speedup": grow_ratio >= 1.15,
        "accept_zero_failed": failed == 0 and wrong == 0,
        "pace_mbps": 8.0,
    }


_TCP_CHILD = r"""
import os, sys, time, json
import faulthandler
# Self-report hangs (a mispaired barrier would otherwise wedge the
# whole phase silently); budget scales with the rank count since n
# processes time-share this host's one core, and is cancelled once the
# timed window ends — teardown must not be hard-killed on a slow run.
faulthandler.dump_traceback_later(420 + 180 * int(sys.argv[2]),
                                  exit=True)
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_compilation_cache_dir',
                  os.path.join({repo!r}, '.jax_cache'))
jax.config.update('jax_persistent_cache_min_compile_time_secs', 5)
sys.path.insert(0, {repo!r})
import numpy as np
import multiverso_tpu as mv
from multiverso_tpu.models.wordembedding import (
    BlockLoader, Dictionary, PSDeviceCorpusTrainer, PSWord2Vec,
    TokenizedCorpus, Word2VecConfig, iter_pair_batches)
rank = int(sys.argv[1]); n = int(sys.argv[2])
# Mixed-role deployment (the reference's -ps_role split): rank 0 is
# worker+server and — being co-located with EVERY shard — keeps the
# zero-copy device pipeline; other ranks are workers whose PS traffic
# crosses the TCP wire with host batches.
role = 'all' if rank == 0 else 'worker'
mv.init(['-machine_file=' + {mf!r}, '-rank=' + str(rank),
         '-ps_role=' + role])
d = Dictionary.load({dict_path!r})
config = Word2VecConfig(embedding_size={dim}, window=5, negative={neg},
                        epochs={epochs}, batch_size={batch},
                        sample=1e-3, use_ps=True, neg_block={neg_block})
model = PSWord2Vec(config, d)


def capped(seed, cap):
    for i, b in enumerate(iter_pair_batches(
            d, {corpus!r}, batch_size={batch}, window=5,
            subsample=1e-3, seed=seed)):
        if i >= cap:
            return
        yield b


# Barrier protocol — 5 per rank, IDENTICAL on both branches (both
# train calls end with one internal cluster barrier: train_epoch's
# epoch-end and train_batches' stream-end): warm-internal, start line,
# timed-internal, exit line, shutdown.
if model._device_path:
    tok = TokenizedCorpus.build(d, {corpus!r})
    trainer = PSDeviceCorpusTrainer(model, tok, 16384,
                                    blocks_per_dispatch=4)
    trainer.train_epoch(seed=99, max_steps=8)   # warm (barrier inside)
    mv.barrier()  # start line
    w0 = model.trained_words
    t0 = time.perf_counter()
    trainer.train_epoch(seed=0, max_steps={dev_blocks})  # barrier inside
    elapsed = time.perf_counter() - t0
else:
    model.train_batches(BlockLoader(model.prepared(capped(99, 4))))
    mv.barrier()  # start line
    w0 = model.trained_words
    t0 = time.perf_counter()
    model.train_batches(BlockLoader(model.prepared(
        capped(rank, {cap}))))   # ends with the stream barrier
    model._drain_pushes()
    elapsed = time.perf_counter() - t0
faulthandler.cancel_dump_traceback_later()
print('TCPRES', json.dumps({{'rank': rank, 'device': model._device_path,
                             'words': model.trained_words - w0,
                             'elapsed': elapsed}}), flush=True)
mv.barrier()
mv.shutdown()
"""


def run_tcp_processes(corpus: str, prebuilt, n: int, tmp: str,
                      cap: int = 24) -> dict:
    """Cross-process PS over the TCP transport (VERDICT r3 #4): n OS
    processes on a localhost machine-file mesh (the reference's ZMQ
    deployment, zmq_net.h:20-61): rank 0 is worker+server (keeping the
    device pipeline under the co-location rule), other ranks are
    workers on the CPU backend. NOTE this box has ONE CPU core — n
    processes time-share it, so aggregate words/s measures transport
    overhead, not scaling headroom."""
    from multiverso_tpu.util.net_util import free_listen_port
    dictionary, _ = prebuilt
    dict_path = os.path.join(tmp, "bench_dict.txt")
    if not os.path.exists(dict_path):
        dictionary.store(dict_path)
    mf = os.path.join(tmp, f"bench_mf_{n}.txt")
    with open(mf, "w") as f:
        # Fresh probed ports per run (free_listen_port scans below the
        # ephemeral range — deliberately NOT bind(0)-assigned, which
        # could be stolen before the child binds): a static port list
        # breaks the whole phase if any earlier crashed run left an
        # orphan holding one.
        for p in [free_listen_port() for _ in range(n)]:
            f.write(f"127.0.0.1:{p}\n")
    code = _TCP_CHILD.format(
        repo=os.path.dirname(os.path.abspath(__file__)), mf=mf,
        dict_path=dict_path, corpus=corpus, dim=DIM, neg=NEG,
        epochs=EPOCHS, batch=BATCH, neg_block=NEG_BLOCK, cap=cap,
        dev_blocks=48)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", code, str(rank), str(n)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for rank in range(n)]
    results = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=1200)
            if p.returncode:
                raise RuntimeError(f"tcp child failed: {err[-300:]}")
            for line in out.splitlines():
                if line.startswith("TCPRES "):
                    results.append(json.loads(line[7:]))
    finally:
        # A raise above (timeout, failed child) must not ORPHAN the
        # sibling ranks: they would keep time-sharing this host's one
        # core and holding their mesh ports for the rest of the bench.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    words = sum(r["words"] for r in results)
    elapsed = max(r["elapsed"] for r in results)
    return {"n_processes": n,
            "aggregate_wps": round(words / elapsed, 0),
            "per_rank_wps": [round(r["words"] / r["elapsed"], 0)
                             for r in results],
            "per_rank_device_path": [bool(r.get("device"))
                                     for r in results]}


def topic_separation(emb: np.ndarray, dictionary,
                     fetch_rows=None) -> float:
    """Within-band minus cross-band cosine similarity of the most
    frequent words of each topic band (quality signal; positive =
    embeddings learned the corpus structure). ``fetch_rows(ids)``
    fetches just the scored rows — a PS table's full-matrix download
    would ship the whole table over the host link for 48 rows."""
    half = VOCAB // 2
    per_band = 24
    band_a, band_b = [], []
    for word, wid in dictionary.word2id.items():
        raw = int(word[1:])
        (band_a if raw < half else band_b).append(wid)
        if len(band_a) >= per_band and len(band_b) >= per_band:
            break
    band_a, band_b = band_a[:per_band], band_b[:per_band]
    if fetch_rows is not None:
        rows = fetch_rows(np.array(band_a + band_b, np.int32))
        a, b = rows[:len(band_a)], rows[len(band_a):]
    else:
        a = emb[band_a]
        b = emb[band_b]
    a = a / np.maximum(np.linalg.norm(a, axis=1, keepdims=True), 1e-9)
    b = b / np.maximum(np.linalg.norm(b, axis=1, keepdims=True), 1e-9)
    within = ((a @ a.T).mean() + (b @ b.T).mean()) / 2
    across = (a @ b.T).mean()
    return within - across


def cpu_baseline(corpus: str) -> dict:
    """Identical fixed-seed run, host CPU backend, separate process —
    the LOSS PARITY twin (same code, same seeds, different backend).
    The performance baseline is ``cpp_baseline`` below."""
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import json, bench\n"
        # Mirror the parent's effective constants so the fixed-seed runs
        # are bit-comparable.
        f"bench.VOCAB={VOCAB}; bench.SENTENCES={SENTENCES}\n"
        f"bench.EPOCHS={EPOCHS}; bench.BATCH={BATCH}\n"
        f"bench.DIM={DIM}; bench.NEG={NEG}\n"
        f"bench.MIN_COUNT={MIN_COUNT}\n"
        f"bench.NEG_BLOCK={NEG_BLOCK}\n"
        f"bench.LOCAL_CENTERS={LOCAL_CENTERS}\n"
        f"bench.LOCAL_DISPATCH={LOCAL_DISPATCH}\n"
        # ALL epochs (VERDICT r3 #8): the banded step cut the CPU twin's
        # per-epoch cost enough to afford the full fixed-seed run, so
        # loss parity covers every epoch, not just epoch 0.
        f"r = bench.run_local({corpus!r}, epochs={EPOCHS},"
        f" schedule_epochs={EPOCHS})\n"
        "print('RES', json.dumps({'wps': r['wps'],"
        " 'epoch_losses': r['epoch_losses']}))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=os.path.dirname(
        os.path.abspath(__file__)), env=env, capture_output=True,
        text=True, timeout=3000)
    for line in out.stdout.splitlines():
        if line.startswith("RES "):
            return json.loads(line[4:])
    raise RuntimeError(f"cpu baseline failed: {out.stderr[-500:]}")


def cpp_baseline(corpus: str, tmp: str, dictionary) -> dict:
    """The honest CPU number to beat: a from-scratch C++ word2vec SGNS
    trainer (native/baseline/word2vec_baseline.cpp — OpenMP hogwild,
    sigmoid table, alias-method negatives; the style of the reference's
    hot loop, ref: Applications/WordEmbedding/src/wordembedding.cpp:
    95-125) run on the SAME corpus with the SAME hyperparameters and
    epochs. Returns its words/s plus the topic-separation quality of
    the embeddings it trained."""
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "native", "baseline",
                       "word2vec_baseline.cpp")
    binary = os.path.join(tmp, "w2v_baseline")
    subprocess.run(["g++", "-O3", "-march=native", "-fopenmp",
                    "-o", binary, src], check=True, capture_output=True)
    vec_path = os.path.join(tmp, "cpp_vectors.bin")
    out = subprocess.run(
        [binary, corpus, vec_path, str(EPOCHS), str(DIM), "5", str(NEG),
         "1e-3", "0.025", str(MIN_COUNT)],
        capture_output=True, text=True, timeout=3000, check=True)
    stats = json.loads(out.stdout.strip().splitlines()[-1])
    emb = np.fromfile(vec_path, dtype=np.float32).reshape(-1, DIM)
    with open(vec_path + ".words") as f:
        cpp_words = [line.rstrip("\n") for line in f]
    # Same vocab sort rules (count desc, then lexicographic) on both
    # sides — verify, then compare quality on identical word sets.
    assert cpp_words[:100] == dictionary.words[:100], \
        "C++ vocab order diverged from the framework dictionary"
    stats["topic_separation"] = round(
        float(topic_separation(emb, dictionary)), 4)
    return stats


def _dispatch_rtt_ms(iters: int) -> float:
    """Per-call dispatch + completion round trip for a tiny jitted op
    (scalar readback per call — the async pipeline would otherwise
    hide it). NOTE: jax.block_until_ready is not reliable on the
    tunneled platform; the float() readback is the sync."""
    import jax
    import jax.numpy as jnp
    tiny = jax.jit(lambda x: x + 1.0)
    s = tiny(jnp.float32(0))
    float(s)
    t0 = time.perf_counter()
    for _ in range(iters):
        s = tiny(s)
        float(s)
    return (time.perf_counter() - t0) / iters * 1e3


def _launch_overhead_samples(blocks: int, per_block: int) -> list:
    """Per-program launch cost: chained (no readback) executions still
    serialize device-side; each sample is one block's mean."""
    import jax
    import jax.numpy as jnp
    tiny = jax.jit(lambda x: x + 1.0)
    s = tiny(jnp.float32(0))
    float(s)
    samples = []
    for _ in range(blocks):
        t0 = time.perf_counter()
        for _ in range(per_block):
            s = tiny(s)
        float(s)
        samples.append((time.perf_counter() - t0) / per_block * 1e3)
    return samples


def _tunnel_rates_mbps(n_floats: int) -> tuple:
    """(upload, download) MB/s through the tunnel: warmed path, fresh
    bytes allocated OUTSIDE the timed window."""
    import jax.numpy as jnp
    probe = np.ones(n_floats, np.float32)
    float(jnp.asarray(probe)[0])  # warm the transfer path
    probe2 = probe * 2.0
    t0 = time.perf_counter()
    dev = jnp.asarray(probe2)
    float(dev[0])
    up = probe.nbytes / (time.perf_counter() - t0) / 1e6
    t0 = time.perf_counter()
    np.asarray(dev)
    down = probe.nbytes / (time.perf_counter() - t0) / 1e6
    return up, down


def weather_probe() -> dict:
    """~10s platform-state snapshot taken before any TIMED phase: the
    tunneled chip's dispatch RTT / program-launch overhead swing 5-50x
    across hours, and a words/s number without the weather it was
    measured in is uninterpretable. Recorded first so even a truncated
    run carries its context (the matrix phase re-measures at the end
    with the same helpers)."""
    rtt_ms = _dispatch_rtt_ms(5)
    launch = _launch_overhead_samples(2, 20)
    up_mbps, _ = _tunnel_rates_mbps(2 << 20)  # 8 MB
    return {"dispatch_roundtrip_ms": round(rtt_ms, 1),
            "program_launch_ms": round(float(np.median(launch)), 3),
            "tunnel_upload_mbps": round(up_mbps, 1)}


def run_wire_codec() -> dict:
    """Pure-host codec phase: compression ratio + encode/decode GB/s on
    a canned power-law sparse gradient (the PS push/pull and ma-mode
    allreduce wire shape), against the REMOVED float64-pair encoding
    (16 B/surviving pair + an 8-byte size record) as the baseline."""
    from multiverso_tpu.util import wire_codec as wc
    rng = np.random.default_rng(7)
    n = 1 << 20  # 4 MB of fp32 — a realistic embedding-push blob
    nnz = n // 20  # 5% density, power-law magnitudes
    blob = np.zeros(n, np.float32)
    idx = np.sort(rng.choice(n, nnz, replace=False))
    blob[idx] = ((rng.pareto(2.0, nnz) + 0.1)
                 * np.sign(rng.standard_normal(nnz))).astype(np.float32)
    old_pair_bytes = 16 * nnz + 8  # float64 pairs + int64 size record

    out = {"blob_elements": n, "density": nnz / n,
           "old_float64_pair_bytes": old_pair_bytes}
    for label, lossy in (("lossless", False), ("lossy", True)):
        frame, _ = wc.encode_blob(blob, lossy=lossy)
        decoded = wc.decode_blob(frame)
        if not lossy:
            np.testing.assert_array_equal(decoded, blob)
        iters = 8
        t0 = time.perf_counter()
        for _ in range(iters):
            wc.encode_blob(blob, lossy=lossy)
        enc_s = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            wc.decode_blob(frame)
        dec_s = (time.perf_counter() - t0) / iters
        out[label] = {
            "tier": wc.tier_name(wc.peek_tier(frame)),
            "wire_bytes": len(frame),
            "ratio_vs_float64_pairs": round(old_pair_bytes / len(frame), 3),
            "ratio_vs_raw": round(blob.nbytes / len(frame), 3),
            "encode_gbps": round(blob.nbytes / enc_s / 1e9, 3),
            "decode_gbps": round(blob.nbytes / dec_s / 1e9, 3),
        }
        if lossy:
            out[label]["max_abs_err"] = \
                round(float(np.abs(decoded - blob).max()), 6)
    return out


@flag_guarded
def _wire_pump(zero_copy: bool, n_msgs: int, rows: int,
               dims: int = 256, shm: bool = False) -> dict:
    """One arm of the ``zero_copy`` phase: large-blob PS-shaped traffic
    over loopback TCP — rank 0 streams ``n_msgs`` Get replies' worth of
    (rows x dims) fp32 payload to rank 1, which echoes each frame's
    blob straight back (the serving read shape: big payloads both
    directions, and the echo re-serializes RECEIVED view-backed blobs).
    Serialization — not the wire — dominates on loopback, which is
    exactly where the copy count shows. ``shm=True`` negotiates the
    pair onto shared-memory rings (docs/MEMORY.md "Below the socket"):
    same traffic, same counters, zero wire syscalls — slots sized so a
    whole frame fits one slot and the receive side parses in place.
    Returns rows/s and the measured copied-bytes-per-payload-byte off
    the WIRE_BYTES_COPIED / WIRE_PAYLOAD_BYTES counters."""
    import threading
    from multiverso_tpu.core.blob import Blob
    from multiverso_tpu.core.message import Message, MsgType
    from multiverso_tpu.runtime.tcp import TcpNet
    from multiverso_tpu.util.configure import set_flag
    from multiverso_tpu.util.dashboard import Dashboard
    from multiverso_tpu.util.net_util import free_listen_port

    set_flag("zero_copy", zero_copy)
    set_flag("buffer_pool_mb", 32 if zero_copy else 0)
    Dashboard.reset()
    eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
    nets = [TcpNet(r, eps) for r in range(2)]
    if shm:
        from multiverso_tpu.runtime.shm import ShmNet
        # 8 slots keeps the echo's in-flight window under the pin
        # valve (half the ring), so frames stay zero-copy end to end.
        set_flag("shm_ring_slots", 8)
        set_flag("shm_slot_kb", 8192)  # a 4 MB frame fits one slot
        nets = [ShmNet(n) for n in nets]
        for n in nets:
            n.enable_shm(0x6B3A, [1 - n.rank])
    try:
        payload = np.arange(rows * dims, dtype=np.float32)
        errs = []

        def echo():
            try:
                for _ in range(n_msgs):
                    msg = nets[1].recv(timeout=120)
                    assert msg is not None
                    reply = msg.create_reply_message()
                    reply.data = list(msg.data)  # re-send the view
                    nets[1].send(reply)
            except Exception as exc:  # noqa: BLE001 - surfaced below
                errs.append(exc)

        server = threading.Thread(target=echo, daemon=True)
        server.start()
        window = 4
        inflight = 0
        t0 = time.perf_counter()
        for i in range(n_msgs):
            msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get,
                          msg_id=i)
            msg.push(Blob(payload))
            nets[0].send(msg)
            inflight += 1
            if inflight >= window:
                assert nets[0].recv(timeout=120) is not None
                inflight -= 1
        for _ in range(inflight):
            assert nets[0].recv(timeout=120) is not None
        elapsed = time.perf_counter() - t0
        server.join(timeout=30)
        assert not errs, errs
        copied = Dashboard.get("WIRE_BYTES_COPIED").count
        payload_bytes = Dashboard.get("WIRE_PAYLOAD_BYTES").count
        pool_hits = Dashboard.get("POOL_HIT").count
        pool_miss = Dashboard.get("POOL_MISS").count
        total_rows = n_msgs * rows * 2  # both directions
        out = {
            "rows_per_sec": round(total_rows / elapsed, 0),
            "payload_mb_per_sec": round(
                n_msgs * payload.nbytes * 2 / elapsed / 1e6, 1),
            "sec": round(elapsed, 4),
            "copied_bytes_per_payload_byte": round(
                copied / max(payload_bytes, 1), 6),
            "pool_hits": pool_hits, "pool_misses": pool_miss,
        }
        if shm:
            out["shm_frames"] = Dashboard.get("SHM_FRAMES").count
            out["shm_bytes_copied"] = \
                Dashboard.get("SHM_BYTES_COPIED").count
        return out
    finally:
        for n in nets:
            n.finalize()


def run_zero_copy() -> dict:
    """Zero-copy wire-path phase (docs/MEMORY.md): the scatter-gather +
    pooled-receive path vs the legacy join/tobytes baseline
    (``-zero_copy=0``) on the SAME traffic — large-blob PS echoes and a
    dense 2-rank ring allreduce over loopback TCP. Acceptance: the
    copied-bytes-per-payload-byte ratio drops >=2x and rows/s improves
    on the large-blob arm; frames stay byte-identical (the golden
    check below + tests/test_zero_copy.py)."""
    from multiverso_tpu.core.blob import Blob
    from multiverso_tpu.core.message import Message, MsgType
    from multiverso_tpu.runtime.tcp import _serialize, serialize_views

    # Inline golden proof on a representative frame: the two
    # serializers emit identical bytes, so the bench's two arms (and
    # mixed-build clusters) speak one wire format.
    probe = Message(src=0, dst=1, msg_type=MsgType.Request_Get,
                    msg_id=77)
    probe.push(Blob(np.arange(4096, dtype=np.float32)))
    probe.push(Blob(b"text payload"))
    views, nbytes = serialize_views(probe)
    flat = _serialize(probe)
    identical = b"".join(bytes(v) for v in views) == flat \
        and nbytes == len(flat)

    n_msgs, rows = 64, 4096  # 4 MB blobs: an embedding-table Get reply

    def best_of(arms):
        """Best-of-2 per arm: the pumps share one GIL with their echo
        threads, so single runs are scheduling-noisy; the max is the
        honest capability number for a throughput arm."""
        runs = [arms() for _ in range(2)]
        return max(runs, key=lambda r: r["rows_per_sec"])

    zc = best_of(lambda: _wire_pump(True, n_msgs, rows))
    base = best_of(lambda: _wire_pump(False, n_msgs, rows))
    out = {
        "frames_byte_identical": identical,
        "blob_mb": round(rows * 256 * 4 / 1e6, 2),
        "zero_copy": zc,
        "copy_baseline": base,
        "copied_ratio_improvement": round(
            base["copied_bytes_per_payload_byte"]
            / max(zc["copied_bytes_per_payload_byte"], 1e-9), 1),
        "rows_per_sec_speedup": round(
            zc["rows_per_sec"] / max(base["rows_per_sec"], 1), 3),
    }
    # Below the socket (docs/MEMORY.md): the same echo traffic with the
    # pair negotiated onto shared-memory rings. Acceptance: rows/s
    # >= 1.3x the loopback-TCP zero-copy arm, and shm_bytes_copied ~ 0
    # (single-slot frames parse in place on the receive side).
    from multiverso_tpu.runtime import shm as shm_mod
    if shm_mod.supported():
        with flag_guard():
            shm_echo = best_of(
                lambda: _wire_pump(True, n_msgs, rows, shm=True))
        out["shm_echo"] = shm_echo
        out["shm_rows_per_sec_speedup_vs_tcp"] = round(
            shm_echo["rows_per_sec"] / max(zc["rows_per_sec"], 1), 3)
    # Allreduce over loopback: the collective's segment frames ride the
    # same framer; dense 4 MB fp32, forced ring, codec on (RAW frames
    # pass the payload as a zero-copy view).
    with flag_guard():
        from multiverso_tpu.util.configure import set_flag
        set_flag("zero_copy", True)
        ar_zc = _allreduce_world(2, "ring", 0.0, False, "tcp", 1 << 20)
        ar_shm = None
        if shm_mod.supported():
            # Enough slots that the engine's out-of-order stash (its
            # pipelined segment window) stays under the pin valve.
            set_flag("shm_ring_slots", 16)
            set_flag("shm_slot_kb", 4096)
            ar_shm = _allreduce_world(2, "ring", 0.0, False, "shm",
                                      1 << 20)
        set_flag("zero_copy", False)
        set_flag("buffer_pool_mb", 0)
        ar_base = _allreduce_world(2, "ring", 0.0, False, "tcp", 1 << 20)
    out["allreduce"] = {
        "zero_copy": ar_zc, "copy_baseline": ar_base,
        "speedup": round(ar_base["sec"] / max(ar_zc["sec"], 1e-9), 3)}
    if ar_shm is not None:
        out["allreduce"]["shm"] = ar_shm
        out["allreduce"]["shm_speedup_vs_tcp"] = round(
            ar_zc["sec"] / max(ar_shm["sec"], 1e-9), 3)
    return out


@flag_guarded
def _allreduce_world(world: int, algo: str, pace_mbps: float,
                     lossy: bool, transport: str, n_elems: int,
                     reps: int = 2, fill: float = 1.0,
                     codec: bool = True, sharded: bool = False) -> dict:
    """One engine configuration: ``world`` thread-ranks allreducing a
    ``n_elems`` fp32 buffer, over LocalFabric or localhost TCP (paced
    to emulate the DCN wire); ``transport="shm"`` wraps the TCP mesh
    in the co-located shared-memory rings (runtime/shm.py). ``fill`` < 1 draws power-law sparse
    inputs (pareto magnitudes on a random support, the MA-delta wire
    shape); ``codec=False`` disables the wire codec — the dense-RAW
    baseline an MA round shipping full parameters pays; ``sharded``
    runs ``sharded_average`` instead (mean semantics). Returns best
    wall time + bytes on wire + the engine's algorithm pick and
    per-rank reduce-state bytes."""
    import threading
    from multiverso_tpu.runtime.allreduce_engine import AllreduceEngine
    from multiverso_tpu.runtime.net import LocalFabric
    from multiverso_tpu.util.configure import set_flag
    from multiverso_tpu.util.net_util import free_listen_port

    set_flag("allreduce_algo", algo)
    set_flag("allreduce_lossy", lossy)
    set_flag("net_pace_mbps", pace_mbps)
    set_flag("wire_codec", codec)
    nets = []
    try:
        if transport in ("tcp", "shm"):
            from multiverso_tpu.runtime.tcp import TcpNet
            eps = [f"127.0.0.1:{free_listen_port()}"
                   for _ in range(world)]
            # Construct INSIDE the try: a bind race on a freed port
            # must clean up the endpoints already built and surface
            # the real error, not a NameError from the finally.
            for r in range(world):
                nets.append(TcpNet(r, eps))
            if transport == "shm":
                from multiverso_tpu.runtime.shm import ShmNet
                nets = [ShmNet(n) for n in nets]
                for n in nets:
                    n.enable_shm(0x6B3A, [r for r in range(world)
                                          if r != n.rank])
        else:
            fabric = LocalFabric(world)
            nets = [fabric.endpoint(r) for r in range(world)]
        engines = [AllreduceEngine(n) for n in nets]
        rng = np.random.default_rng(11)
        if fill < 1.0:
            nnz = max(int(n_elems * fill), 1)
            inputs = []
            for _ in range(world):
                x = np.zeros(n_elems, np.float32)
                idx = np.sort(rng.choice(n_elems, nnz, replace=False))
                x[idx] = ((rng.pareto(2.0, nnz) + 0.1)
                          * np.sign(rng.standard_normal(nnz))
                          ).astype(np.float32)
                inputs.append(x)
        else:
            # Bounded dynamic range: int8-eligible, the shape of
            # normalized model-average deltas.
            inputs = [(np.sign(rng.standard_normal(n_elems))
                       * rng.uniform(0.5, 1.5, n_elems))
                      .astype(np.float32) for _ in range(world)]
        expected = np.sum([x.astype(np.float64) for x in inputs], axis=0)
        if sharded:
            expected = expected / world
        results = [None] * world
        best = float("inf")
        wire = 0

        def call(r):
            if sharded:
                return engines[r].sharded_average(inputs[r])
            return engines[r].allreduce(inputs[r])

        for _ in range(reps):
            before = sum(n.bytes_sent for n in nets)
            t0 = time.perf_counter()
            threads = [threading.Thread(
                target=lambda r=r: results.__setitem__(r, call(r)))
                for r in range(world)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
                assert not t.is_alive(), "allreduce bench deadlocked"
            best = min(best, time.perf_counter() - t0)
            wire = sum(n.bytes_sent for n in nets) - before
        tol = 0.2 if lossy else 1e-3
        np.testing.assert_allclose(results[0], expected, rtol=tol,
                                   atol=tol)
        return {"sec": round(best, 4), "wire_mb": round(wire / 1e6, 3),
                "algo": engines[0].last_algo,
                "reduce_state_mb": round(
                    engines[0].last_reduce_state_bytes / 1e6, 3)}
    finally:
        # Flag restore is structural now (@flag_guarded).
        if transport in ("tcp", "shm"):
            for n in nets:
                n.finalize()


@flag_guarded
def _ma_overlap_stall(pace_mbps: float = 100.0) -> dict:
    """MACorpusTrainer sync vs overlap over a paced 2-rank TCP wire:
    same seeds, same schedule — bit-identical embeddings required —
    with MA_COMM_STALL recording how much of the communication the
    trainer actually waited on in each mode."""
    import threading
    import types
    from multiverso_tpu.models.wordembedding import (
        Dictionary, MACorpusTrainer, TokenizedCorpus, Word2Vec,
        Word2VecConfig)
    from multiverso_tpu.runtime.tcp import TcpNet
    from multiverso_tpu.util.configure import set_flag
    from multiverso_tpu.util.dashboard import Dashboard
    from multiverso_tpu.util.net_util import free_listen_port

    from multiverso_tpu.runtime import device_lock

    rng = np.random.default_rng(0)
    vocab = [f"w{i}" for i in range(2000)]
    lines = [" ".join(rng.choice(vocab, size=20)) for _ in range(400)]
    path = os.path.join(tempfile.mkdtemp(), "ma_corpus.txt")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    d = Dictionary.build(path, min_count=1)
    tok = TokenizedCorpus.build(d, path)
    set_flag("allreduce_algo", "ring")
    # Pin the LOSSLESS contract explicitly: the bit-identical check
    # below is about sync-vs-overlap scheduling, and a lossy flag
    # leaked from an earlier phase would silently measure DENSE_F16
    # transfers instead.
    set_flag("allreduce_lossy", False)
    set_flag("net_pace_mbps", pace_mbps)
    # Two thread-ranks dispatch sharded trainer programs in one
    # process: serialize device work like LocalCluster does
    # (runtime/device_lock.py) so the bench can't hit the XLA CPU
    # pool wedge. Host-side comm (the thing measured) still overlaps.
    device_lock.enable()

    def run_mode(overlap: bool):
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        nets = [TcpNet(r, eps) for r in range(2)]
        mon = Dashboard.get("MA_COMM_STALL")
        stall0, count0 = mon.elapse, mon.count
        embs = [None, None]
        rounds = [0, 0]
        errs = [None, None]

        def body(rank):
            try:
                config = Word2VecConfig(
                    embedding_size=64, window=3, epochs=2,
                    init_learning_rate=0.02, batch_size=1024,
                    sample=0, negative=3, seed=17)
                model = Word2Vec(config, d)
                # avg_every=4 groups of 1024 centers: enough device
                # compute between averages to actually hide the ~80ms
                # the 1MB parameter allreduce spends on the paced wire.
                trainer = MACorpusTrainer(
                    model, tok, avg_every=4, overlap=overlap,
                    zoo=types.SimpleNamespace(net=nets[rank]),
                    centers_per_step=1024, steps_per_dispatch=1)
                for epoch in range(2):
                    trainer.train_epoch(seed=epoch)
                trainer.finish()
                embs[rank] = np.asarray(model._emb_in).copy()
                rounds[rank] = trainer.comm_rounds
            except BaseException as exc:  # noqa: BLE001
                errs[rank] = exc

        t0 = time.perf_counter()
        threads = [threading.Thread(target=body, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        hung = [t.name for t in threads if t.is_alive()]
        wall = time.perf_counter() - t0
        for n in nets:
            n.finalize()
        for exc in errs:
            if exc is not None:
                raise exc
        # A silently hung rank must fail the phase, not report
        # half-measured stalls (or compare two None embeddings as
        # bit-identical).
        assert not hung, f"ma trainer rank hung: {hung}"
        return {"stall_ms": round(mon.elapse - stall0, 1),
                "stall_samples": mon.count - count0,
                "wall_sec": round(wall, 2),
                "comm_rounds": rounds[0]}, embs

    try:
        sync, sync_embs = run_mode(False)
        over, over_embs = run_mode(True)
    finally:
        device_lock.disable()
        # Flag restore is structural now (@flag_guarded).
    identical = all(np.array_equal(sync_embs[r], over_embs[r])
                    for r in range(2))
    return {
        "emulated_wire_mbps": pace_mbps,
        "sync": sync, "overlap": over,
        "stall_reduction": round(
            sync["stall_ms"] / max(over["stall_ms"], 1e-3), 3),
        "bit_identical_sync_vs_overlap": identical,
    }


def _sparse_allreduce_points(n: int, pace: float,
                             dense_ring: dict) -> dict:
    """Sparse-stream tier points (docs/ALLREDUCE.md): power-law blobs
    at 1%/5%/20% fill on the same logical size, over the paced TCP
    wire. ``dense_ring`` is the ring on a DENSE payload of that size —
    its segments fail ``worth_encoding`` so every frame rides RAW: the
    bytes an MA round shipping full parameters pays today (the codec
    stays negotiated-on but inert; a future ``worth_encoding`` change
    that starts encoding dense payloads would shift this baseline's
    meaning). Also vs the ring WITH per-segment codec sparse encoding
    engaged on the same SPARSE payload (the strongest dense-path
    configuration). Plus the dense-input auto regression (the nnz
    probe is the only added cost) and the sharded-average
    reduce-state ratio."""
    out = {}
    for fill in (0.01, 0.05, 0.20):
        point = {}
        for world in (2, 3):
            sp = _allreduce_world(world, "auto", pace, False, "tcp", n,
                                  fill=fill)
            base = dense_ring[world]
            point[f"{world}rank"] = {
                **sp,
                "bytes_vs_dense_ring": round(
                    sp["wire_mb"] / base["wire_mb"], 4),
                "speedup_vs_dense_ring": round(
                    base["sec"] / sp["sec"], 3),
            }
        out[f"fill_{int(fill * 100)}pct"] = point
    # The strongest dense-path config on the same 5% payload: the ring
    # with per-segment sparse codec frames (partial sums still ride
    # every hop and densify; the sparse tier ships each contribution
    # once).
    out["ring_codec_5pct_3rank"] = _allreduce_world(
        3, "ring", pace, False, "tcp", n, fill=0.05)
    # Dense inputs above break-even: auto (probe + pick) vs forced
    # ring — the regression budget is 5%.
    auto_dense = _allreduce_world(3, "auto", pace, False, "tcp", n)
    out["dense_auto"] = {
        **auto_dense,
        "regression_vs_forced_ring": round(
            auto_dense["sec"] / dense_ring[3]["sec"], 3),
    }
    # Sharded average: per-rank reduce state ~ 1/world of the buffer.
    sh = _allreduce_world(3, "auto", 0.0, False, "local", n,
                          fill=0.05, sharded=True)
    out["sharded_avg_3rank"] = {
        **sh,
        "reduce_state_vs_buffer": round(
            sh["reduce_state_mb"] / (n * 4 / 1e6), 4),
    }
    return out


@flag_guarded
def _ma_sharded_arm(pace_mbps: float = 200.0) -> dict:
    """MACorpusTrainer sharded (delta-vs-last-average over the sparse
    sharded collective) vs the dense MA trainer on the same schedule,
    over a paced 2-rank TCP wire: bytes on wire, wall, measured delta
    fill, per-rank reduce-state — and the lossless bit-identity proof:
    the sharded run's embeddings equal the SAME delta schedule forced
    down the unchunked dense ring, bit for bit."""
    import threading
    import types
    from multiverso_tpu.models.wordembedding import (
        Dictionary, MACorpusTrainer, TokenizedCorpus, Word2Vec,
        Word2VecConfig)
    from multiverso_tpu.runtime.tcp import TcpNet
    from multiverso_tpu.runtime import device_lock
    from multiverso_tpu.util.configure import set_flag
    from multiverso_tpu.util.dashboard import Dashboard, samples
    from multiverso_tpu.util.net_util import free_listen_port

    rng = np.random.default_rng(3)
    # Zipf token draws over a wide vocabulary: each averaging round
    # touches only the rows its batches hit, so the delta is sparse —
    # the regime the sparse tier exists for.
    vocab = [f"w{i}" for i in range(12000)]
    probs = 1.0 / np.arange(1, len(vocab) + 1) ** 1.3
    probs /= probs.sum()
    lines = [" ".join(rng.choice(vocab, size=20, p=probs))
             for _ in range(700)]
    path = os.path.join(tempfile.mkdtemp(), "ma_sparse_corpus.txt")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    d = Dictionary.build(path, min_count=1)
    tok = TokenizedCorpus.build(d, path)
    set_flag("net_pace_mbps", pace_mbps)
    set_flag("allreduce_lossy", False)
    device_lock.enable()

    def run_mode(sharded: bool, dense_ring_delta: bool = False):
        eps = [f"127.0.0.1:{free_listen_port()}" for _ in range(2)]
        nets = [TcpNet(r, eps) for r in range(2)]
        if dense_ring_delta:
            # Same delta schedule, dense collective: route
            # sharded_average through allreduce/n on the UNCHUNKED
            # ring (one chunk = the sharded fold's association).
            set_flag("allreduce_algo", "ring")
            set_flag("allreduce_chunk_kb", 1 << 20)
            for net in nets:
                net.sharded_average = types.MethodType(
                    lambda self, arr, slot=None:
                    self.allreduce(arr, slot) / self.size, net)
        else:
            set_flag("allreduce_algo", "auto")
        mon = Dashboard.get("MA_COMM_STALL")
        stall0 = mon.elapse
        embs = [None, None]
        errs = [None, None]
        rounds = [0, 0]

        def body(rank):
            try:
                config = Word2VecConfig(
                    embedding_size=64, window=2, epochs=1,
                    init_learning_rate=0.02, batch_size=1024,
                    sample=0, negative=2, seed=23)
                model = Word2Vec(config, d)
                trainer = MACorpusTrainer(
                    model, tok, avg_every=1, overlap=True,
                    sharded=sharded,
                    zoo=types.SimpleNamespace(net=nets[rank]),
                    centers_per_step=256, steps_per_dispatch=1)
                trainer.train_epoch(seed=0, max_steps=24)
                trainer.finish()
                embs[rank] = np.asarray(model._emb_in).copy()
                rounds[rank] = trainer.comm_rounds
            except BaseException as exc:  # noqa: BLE001
                errs[rank] = exc

        t0 = time.perf_counter()
        threads = [threading.Thread(target=body, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        hung = [t.name for t in threads if t.is_alive()]
        wall = time.perf_counter() - t0
        wire = sum(n.bytes_sent for n in nets)
        state = max(
            getattr(getattr(n, "_allreduce_engine", None),
                    "last_reduce_state_bytes", 0) for n in nets)
        for n in nets:
            n.finalize()
        for exc in errs:
            if exc is not None:
                raise exc
        assert not hung, f"ma trainer rank hung: {hung}"
        return {"wall_sec": round(wall, 2),
                "wire_mb": round(wire / 1e6, 2),
                "stall_ms": round(mon.elapse - stall0, 1),
                "comm_rounds": rounds[0],
                "reduce_state_mb": round(state / 1e6, 3)}, embs

    try:
        # Dense first: it pays the one-time trainer jit compile, so
        # the two delta arms (and their bit-identity) compare warm.
        dense_res, _ = run_mode(False)
        fill_s = samples("SPARSE_FILL[input]")
        fills_before = fill_s.count
        sharded_res, sharded_embs = run_mode(True)
        fills = fill_s.export_recent(
            max(fill_s.count - fills_before, 1))
        ring_res, ring_embs = run_mode(True, dense_ring_delta=True)
    finally:
        device_lock.disable()
        # Flag restore is structural now (@flag_guarded).
    identical = all(np.array_equal(sharded_embs[r], ring_embs[r])
                    for r in range(2))
    params_mb = sharded_embs[0].size * 2 * 4 / 1e6  # emb_in + emb_out
    return {
        "emulated_wire_mbps": pace_mbps,
        "model_params_mb": round(params_mb, 2),
        "sharded_sparse": sharded_res,
        "dense_ma": dense_res,
        "delta_dense_ring": ring_res,
        "wire_reduction_vs_dense_ma": round(
            dense_res["wire_mb"] / max(sharded_res["wire_mb"], 1e-6),
            3),
        "stall_reduction_vs_dense_ma": round(
            dense_res["stall_ms"] / max(sharded_res["stall_ms"], 1e-3),
            3),
        "note": "dense_ma runs first and absorbs the one-time trainer "
                "jit compile in wall_sec; wire/stall compare cleanly",
        "median_delta_fill": round(float(np.median(fills)), 4)
        if fills else None,
        "reduce_state_vs_params": round(
            sharded_res["reduce_state_mb"] / params_mb, 4),
        "bit_identical_sharded_vs_dense_ring_delta": identical,
    }


@flag_guarded
def run_allreduce() -> dict:
    """Collective-stack phase: chunked pipelined ring vs monolithic
    recursive halving, lossless vs int8 error-feedback, on a 4 MB fp32
    buffer at 2 and 3 ranks, in-process and over localhost TCP paced to
    DCN-class rates; plus the MA trainer sync-vs-overlap stall
    comparison. All ranks share this host's single core, so in-process
    and codec-CPU numbers UNDERSTATE the multi-host win."""
    n = 2 << 20  # 8 MB fp32 (acceptance floor is >= 4 MB)
    pace = 200.0  # between the 49 Mbps tunnel and localhost; stable
    # against this host's scheduler noise (one core for everything)
    out = {"buffer_mb": round(n * 4 / 1e6, 1),
           "emulated_wire_mbps": pace,
           "note": "single-core host: every rank, writer thread and "
                   "codec pass time-shares one core"}
    dense_ring = {}
    for world in (2, 3):
        mono = _allreduce_world(world, "rhalving", pace, False,
                                "tcp", n)
        ring = _allreduce_world(world, "ring", pace, False,
                                "tcp", n)
        dense_ring[world] = ring
        ring_i8 = _allreduce_world(world, "ring", pace, True,
                                   "tcp", n)
        local = {
            "monolithic": _allreduce_world(world, "rhalving", 0.0,
                                           False, "local", n),
            "ring": _allreduce_world(world, "ring", 0.0, False,
                                     "local", n)}
        out[f"tcp_{world}rank"] = {
            "monolithic_rhalving": mono,
            "chunked_ring": ring,
            "chunked_ring_int8": ring_i8,
            "ring_speedup": round(mono["sec"] / ring["sec"], 3),
            "int8_wire_reduction": round(
                ring["wire_mb"] / ring_i8["wire_mb"], 3),
            "int8_speedup": round(mono["sec"] / ring_i8["sec"], 3),
        }
        out[f"inprocess_{world}rank"] = local
    # The BENCH_r05-class slow wire (tunnel ~49 Mbps up): where the
    # int8 byte cut dominates the codec CPU cost outright.
    slow_mono = _allreduce_world(3, "rhalving", 100.0, False,
                                 "tcp", n, reps=1)
    slow_i8 = _allreduce_world(3, "ring", 100.0, True, "tcp", n,
                               reps=1)
    out["tcp_3rank_100mbps"] = {
        "monolithic_rhalving": slow_mono,
        "chunked_ring_int8": slow_i8,
        "int8_speedup": round(slow_mono["sec"] / slow_i8["sec"], 3),
    }
    # Headline numbers the acceptance criteria read.
    out["ring_speedup"] = out["tcp_3rank"]["ring_speedup"]
    out["int8_wire_reduction"] = \
        out["tcp_3rank"]["int8_wire_reduction"]
    # Sparse-stream tier points + the sharded MA arm
    # (docs/ALLREDUCE.md sparse tier; acceptance: 5% fill bytes
    # <= 0.25x / speedup >= 1.5x vs the dense ring, dense auto
    # regression <= 5%, reduce-state ~ 1/world).
    out["sparse"] = _sparse_allreduce_points(n, pace, dense_ring)
    out["sparse_bytes_vs_dense_ring"] = \
        out["sparse"]["fill_5pct"]["3rank"]["bytes_vs_dense_ring"]
    out["sparse_speedup_vs_dense_ring"] = \
        out["sparse"]["fill_5pct"]["3rank"]["speedup_vs_dense_ring"]
    out["ma_sharded"] = _ma_sharded_arm()
    out["ma_overlap"] = _ma_overlap_stall()
    return out


def utilization(pairs_per_sec: float, centers_per_sec: float,
                window: int = 5) -> dict:
    """Achieved FLOP/s and HBM bytes/s for the BANDED SGNS step vs chip
    peaks.

    Per valid pair (D = DIM): pos dot fwd+bwd = 6*D. Negatives are
    drawn per BLOCK of NEG_BLOCK centers (K per block, logits per
    center): 6*D*K per center. ``centers_per_sec`` is the exact
    post-subsampling token rate tracked by the trainer. Bytes (banded
    form): per center ~(2 + K/NEG_BLOCK) rows touched (v + band +
    shared negs), each gathered once (read) and scatter-added once
    (read+write) = 3 * D * 4 bytes per row."""
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "unknown").lower()
    flops_peak, hbm_peak = 197e12, 819e9
    for key, peaks in _CHIP_PEAKS.items():
        if key in kind:
            flops_peak, hbm_peak = peaks
            break
    achieved_flops = 6 * DIM * (pairs_per_sec + NEG * centers_per_sec)
    achieved_bytes = centers_per_sec * 3 * (2 + NEG / NEG_BLOCK) \
        * DIM * 4
    # Elementwise logit/grad formation over the band: per window offset
    # the step reads a [C, D] band slice and the [C, D] center rows
    # (forward) and re-reads both plus writes grads (backward) — ~6
    # HBM passes per offset IF none of it stays resident in VMEM. An
    # upper-bound model, reported separately from the hard gather/
    # scatter floor (XLA may fuse much of it).
    elementwise_bytes = centers_per_sec * 6 * (2 * window) * DIM * 4
    return {
        "device_kind": kind,
        "achieved_tflops": round(achieved_flops / 1e12, 4),
        "mfu": round(achieved_flops / flops_peak, 6),
        "achieved_gbps": round(achieved_bytes / 1e9, 2),
        "hbm_utilization": round(achieved_bytes / hbm_peak, 4),
        "elementwise_gbps_upper_bound": round(elementwise_bytes / 1e9,
                                              2),
        "hbm_utilization_with_elementwise": round(
            (achieved_bytes + elementwise_bytes) / hbm_peak, 4),
    }


def step_decomposition(local: dict, matrix: dict) -> dict:
    """MEASURED wall-clock decomposition of the banded local step
    (VERDICT r4 weak #4): convert the step's known row traffic into
    time shares using the SAME-RUN microbench rates (slope-timed
    scatter/gather GB/s, per-program launch ms) — the remainder is
    elementwise compute + XLA overhead. Fractions of 1s of wall."""
    cps = local["centers_per_sec"]
    rows_per_center = 2 + NEG / NEG_BLOCK  # v + band + shared negs
    gather_Bps = cps * rows_per_center * DIM * 4
    scatter_Bps = cps * rows_per_center * DIM * 4 * 2  # read+write
    out = {"note": "fraction of each wall-clock second attributed by "
                   "measured microbench rates; residual = elementwise "
                   "compute + fusion + XLA overhead"}
    sg = matrix.get("scatter_32k_rows_gbps")
    gg = matrix.get("gather_256k_rows_gbps")
    lm = matrix.get("program_launch_ms")
    total = 0.0
    if sg:
        out["scatter_frac"] = round(scatter_Bps / (sg * 1e9), 4)
        total += out["scatter_frac"]
    if gg:
        out["gather_frac"] = round(gather_Bps / (gg * 1e9), 4)
        total += out["gather_frac"]
    if lm and local.get("groups_per_sec"):
        out["launch_frac"] = round(
            local["groups_per_sec"] * lm / 1e3, 4)
        total += out["launch_frac"]
    out["residual_frac"] = round(max(1.0 - total, 0.0), 4)
    return out


def run_client_cache() -> dict:
    """Client-cache phase: repeated power-law row-Get workload (the
    wordembedding access shape, SparCML's observation) through the full
    PS stack, cached vs uncached, plus the trainer-shaped prefetch
    double-buffer. Reports hit rate, effective Get throughput, and the
    per-step pull-stall with and without prefetch. Acceptance: >=1.5x
    effective Get throughput on the hot-row workload."""
    import multiverso_tpu as mv
    from multiverso_tpu.util.configure import set_flag

    num_row, num_col, per_batch = 1 << 15, 64, 256
    pool, passes = 80, 3  # epoch-style: the pool repeats, as a
    #   trainer's working set does across epochs
    staleness = 24  # versions are per SHARD (any add ages every
    #   entry), so the bound must cover the ~10 adds-per-pass x the
    #   passes between revisits of a pool batch
    rng = np.random.default_rng(11)
    ranks = np.arange(1, num_row + 1)
    probs = 1.0 / ranks  # Zipf(1.0) row popularity
    probs /= probs.sum()
    batches = [np.unique(rng.choice(num_row, size=per_batch,
                                    p=probs)).astype(np.int32)
               for _ in range(pool)]
    stream = batches * passes
    hot = np.unique(rng.choice(256, size=64)).astype(np.int32)

    def warm(table):
        """One untimed pass: identical in BOTH arms, so jit/bucket
        compiles never contaminate the timed window (the cached arm
        additionally enters the timed window populated — the steady
        state the phase measures)."""
        for ids in batches:
            table.get_rows(ids)

    def workload(table):
        """Timed Get stream with periodic hot-row adds riding along
        (every 24 gets), so invalidation/re-population is priced in.
        Each add is followed by the idiomatic recovery prefetch of the
        rows it dirtied (one async roundtrip restores them for every
        later Get; a no-op in the uncached arm, so both arms run the
        identical call sequence)."""
        t0 = time.perf_counter()
        for i, ids in enumerate(stream):
            table.get_rows(ids)
            if i % 24 == 23:
                table.add_rows(hot, np.ones((hot.size, num_col),
                                            np.float32))
                table.prefetch_rows_async(hot)
        return time.perf_counter() - t0

    def trainer_shaped(table, prefetch):
        """Double-buffer stand-in: prefetch batch i+1, 'compute' 2 ms
        (simulated device step), then pull batch i; returns the mean
        pull-stall only (the compute sleep is constant across arms)."""
        stall = 0.0
        steps = min(60, len(stream))
        for i in range(steps):
            if prefetch and i + 1 < steps:
                table.prefetch_rows_async(stream[i + 1])
            time.sleep(0.002)
            t0 = time.perf_counter()
            table.get_rows(stream[i])
            stall += time.perf_counter() - t0
        return stall / steps

    out = {"num_row": num_row, "num_col": num_col,
           "batch_pool": pool, "passes": passes,
           "rows_per_get": per_batch, "max_get_staleness": staleness}

    mv.init([])  # default flags: cache disabled
    table = mv.create_matrix_table(num_row, num_col)
    table.add_rows(batches[0], np.ones((batches[0].size, num_col),
                                       np.float32))
    warm(table)
    uncached = workload(table)
    stall_plain = trainer_shaped(table, prefetch=False)
    mv.shutdown()

    with flag_guard():  # flag state survives shutdown/init cycles —
        # a leak (even via a mid-phase exception, which _Result.run
        # swallows) would turn the cache on for every later phase's
        # default-flag numbers. The guard restores EVERY flag.
        mv.init([])
        set_flag("max_get_staleness", staleness)  # before table creation
        table = mv.create_matrix_table(num_row, num_col)
        table.add_rows(batches[0], np.ones((batches[0].size, num_col),
                                           np.float32))
        warm(table)
        before = dict(table._row_cache.stats)
        cached = workload(table)
        after = table._row_cache.stats
        timed_hits = after["hits"] - before["hits"]
        timed_total = timed_hits + after["misses"] - before["misses"]
        stall_prefetch = trainer_shaped(table, prefetch=True)
        mv.shutdown()

    timed_rows_hit = after["rows_hit"] - before["rows_hit"]
    timed_rows = timed_rows_hit + after["rows_missed"] \
        - before["rows_missed"]
    out.update(
        hit_rate=round(timed_hits / max(timed_total, 1), 4),
        row_hit_rate=round(timed_rows_hit / max(timed_rows, 1), 4),
        uncached_gets_per_sec=round(len(stream) / uncached, 1),
        cached_gets_per_sec=round(len(stream) / cached, 1),
        effective_get_speedup=round(uncached / cached, 3),
        stall_ms_per_step_no_prefetch=round(stall_plain * 1e3, 3),
        stall_ms_per_step_prefetch=round(stall_prefetch * 1e3, 3),
        prefetch_stall_reduction=round(
            stall_plain / max(stall_prefetch, 1e-9), 3))
    return out


@flag_guarded
def run_server_fusion() -> dict:
    """Server-side request fusion phase (runtime/fusion.py;
    docs/SERVER_ENGINE.md): three client ranks hammer ONE server with
    a Zipf(1.6) Get/Add row mix — the multi-client shape where the
    server mailbox actually backs up — over the co-located shm rings
    and over paced localhost TCP, with fusion off (-server_fuse_max=1)
    vs on (16). Each server dispatch is paced by an emulated tunnel
    launch RTT (the device twin of -net_pace_mbps; this 1-core host's
    ~40us CPU launches would otherwise drown the fixed cost fusion
    amortizes in thread-scheduling noise). Reports rows/s, device
    dispatches per 1k requests, fused-batch p50/p99, cross-request
    dedup rows, and a post-run bit-identity check of a deterministic
    read against the fusion-off arm. Acceptance: >=1.5x rows/s
    fused-on and a >=3x dispatch cut on at least one transport."""
    import multiverso_tpu as mv
    from multiverso_tpu.runtime import shm as shm_mod
    from multiverso_tpu.runtime.cluster import LocalCluster
    from multiverso_tpu.runtime.tcp import TcpNet
    from multiverso_tpu.util.configure import set_flag
    from multiverso_tpu.util.dashboard import Dashboard, samples
    from multiverso_tpu.util.net_util import free_listen_port

    world, num_row, num_col = 3, 1 << 12, 32
    iters, per_get, window, pace_mbps = 256, 16, 32, 150.0
    # Per-dispatch launch pacing: this host's XLA CPU launches in
    # ~40us, but the deployment target is a TUNNELED device where the
    # dispatch RTT runs ~1ms and swings 5-50x with tunnel weather
    # (program_launch_ms / launch_big_ms, measured elsewhere in this
    # bench) — the regime whose fixed cost fusion amortizes. Sleeping
    # launch_ms inside each server dispatch is the device twin of
    # -net_pace_mbps emulating the DCN wire; both arms pay it per
    # PROGRAM, so the ratio isolates exactly the dispatch-count cut.
    launch_ms = 2.0
    ranks = np.arange(1, num_row + 1, dtype=np.float64)
    probs = ranks ** -1.6  # Zipf(1.6): hot heads => cross-request
    probs /= probs.sum()   # duplicate rows for the fused-Get dedup
    n_requests = world * (iters + iters // 8)

    def body(rank):
        # Windowed async-add pipeline (the trainer push shape) with a
        # sync Get every 4th step riding the backlog: clients keep
        # streaming while the server drains, so the serial arm pays
        # one dispatch per message at full mailbox pressure. The
        # client Get register allows only ONE Get in flight per
        # table, so the depth fusion feeds on comes from the add
        # window — 3 clients x window deep.
        from collections import deque
        rng = np.random.default_rng(101 + rank)
        table = mv.create_matrix_table(num_row, num_col, np.float32)
        if rank == 0:
            # Rank 0 hosts the server table ("all" role, registered
            # inline by create): pace its two dispatch sites with the
            # emulated tunnel launch RTT (see launch_ms above). The
            # sleep sits where the real launch stall sits — inside
            # the server's table-locked dispatch — and releases the
            # GIL, exactly like a host thread blocked on the tunnel.
            stab = mv.current_zoo()._server_tables[0]
            real_gather = stab._gather
            real_apply = stab._engine.apply_rows

            def paced_gather(*a):
                time.sleep(launch_ms / 1e3)
                return real_gather(*a)

            def paced_apply(*a, **kw):
                time.sleep(launch_ms / 1e3)
                return real_apply(*a, **kw)

            stab._gather = paced_gather
            stab._engine.apply_rows = paced_apply
        batches = [np.unique(rng.choice(num_row, size=per_get,
                                        p=probs)).astype(np.int32)
                   for _ in range(iters)]
        delta = np.ones((per_get, num_col), np.float32)
        mv.current_zoo().barrier()
        t0 = time.perf_counter()
        rows = 0
        pend = deque()
        for i, ids in enumerate(batches):
            pend.append(table.add_rows_async(ids, delta[:ids.size]))
            rows += int(ids.size)
            if len(pend) >= window:
                table.wait(pend.popleft())
            if i % 8 == 7:
                table.get_rows(ids)
                rows += int(ids.size)
        for msg_id in pend:
            table.wait(msg_id)
        elapsed = time.perf_counter() - t0
        mv.current_zoo().barrier()
        # Post-barrier deterministic read: every client's adds are
        # acked, so the table state is a fixed function of the seeds
        # — the fused arm must reproduce it BIT-identically.
        final = np.array(
            table.get_rows(np.arange(256, dtype=np.int32)), copy=True)
        mv.current_zoo().barrier()
        return elapsed, rows, final

    def arm(transport: str, fuse_max: int) -> dict:
        # Pacing must be set BEFORE TcpNet construction (the writer
        # loop samples the flag once at connect).
        set_flag("net_pace_mbps", pace_mbps if transport == "tcp"
                 else 0.0)
        nets = []
        try:
            eps = [f"127.0.0.1:{free_listen_port()}"
                   for _ in range(world)]
            for r in range(world):
                nets.append(TcpNet(r, eps))
            if transport == "shm":
                from multiverso_tpu.runtime.shm import ShmNet
                nets = [ShmNet(n) for n in nets]
                for n in nets:
                    n.enable_shm(0x51F5, [r for r in range(world)
                                          if r != n.rank])
            disp0 = Dashboard.get("SERVER_DEVICE_DISPATCHES").count
            dedup0 = Dashboard.get("SERVER_FUSE_DEDUP_ROWS").count
            batch_mon = samples("SERVER_FUSE_BATCH")
            batch0 = batch_mon.snapshot()["count"]
            cluster = LocalCluster(
                world, argv=[f"-server_fuse_max={fuse_max}"],
                roles=["all", "worker", "worker"], nets=nets)
            cluster.timeout = 240.0
            res = cluster.run(body)
            disp = Dashboard.get("SERVER_DEVICE_DISPATCHES").count \
                - disp0
            dedup = Dashboard.get("SERVER_FUSE_DEDUP_ROWS").count \
                - dedup0
            fused_batches = batch_mon.snapshot()["count"] - batch0
            # This arm's batch sizes only: the monitor is process-
            # global and the serial arm ran before us.
            recent = batch_mon.export_recent(fused_batches) \
                if fused_batches else []
            sec = max(e for e, _, _ in res)
            rows = sum(r for _, r, _ in res)
            out = {"sec": round(sec, 4),
                   "final": res[0][2],
                   "rows_per_sec": round(rows / max(sec, 1e-9), 1),
                   "device_dispatches": disp,
                   "dispatches_per_1k_requests": round(
                       disp * 1000.0 / n_requests, 1),
                   "fused_batches": fused_batches,
                   "dedup_rows": dedup}
            if recent:
                out["fused_batch_p50"] = float(
                    np.percentile(recent, 50))
                out["fused_batch_p99"] = float(
                    np.percentile(recent, 99))
            return out
        finally:
            for n in nets:  # idempotent: Zoo.stop finalizes the nets
                n.finalize()  # it started; this covers setup failures

    out = {"world": world, "clients": world, "num_row": num_row,
           "num_col": num_col, "rows_per_get": per_get,
           "iters_per_client": iters, "zipf_alpha": 1.6,
           "tcp_pace_mbps": pace_mbps,
           "emulated_launch_ms": launch_ms}
    def best_of(transport: str, fuse_max: int, reps: int = 2) -> dict:
        # Best-of-N: every virtual rank time-shares this host's single
        # core, so one unlucky scheduler quantum can swing an arm far
        # more than the effect under measurement.
        runs = [arm(transport, fuse_max) for _ in range(reps)]
        return max(runs, key=lambda r: r["rows_per_sec"])

    transports = ["tcp"] + (["shm"] if shm_mod.supported() else [])
    for transport in transports:
        serial = best_of(transport, 1)
        fused = best_of(transport, 16)
        identical = bool(np.array_equal(serial.pop("final"),
                                        fused.pop("final")))
        out[transport] = {
            "fuse_off": serial, "fuse_on": fused,
            "rows_per_sec_speedup": round(
                fused["rows_per_sec"]
                / max(serial["rows_per_sec"], 1e-9), 3),
            "dispatch_cut": round(
                serial["dispatches_per_1k_requests"]
                / max(fused["dispatches_per_1k_requests"], 1e-9), 2),
            "gets_bit_identical": identical}
    return out


@flag_guarded
def run_observability() -> dict:
    """Tracing-overhead phase (docs/OBSERVABILITY.md): the PS matrix
    Get hot path at -trace_sample_rate off / 1% / 100%, identical call
    sequences, reporting rows/s per arm. 'Off' runs twice so the
    repeat delta exposes the platform noise floor the comparisons sit
    on; the per-request cost of the disabled sampling hook is also
    microbenched directly, giving a structural upper bound on what the
    off path adds vs a pre-trace build (acceptance: <= 1%)."""
    import multiverso_tpu as mv
    from multiverso_tpu.util import tracing
    from multiverso_tpu.util.configure import set_flag

    num_row, num_col, per_batch, n_gets = 1 << 14, 32, 256, 480
    rng = np.random.default_rng(7)
    stream = [np.unique(rng.integers(0, num_row, size=per_batch))
              .astype(np.int32) for _ in range(n_gets)]

    out = {"num_row": num_row, "num_col": num_col,
           "rows_per_get": per_batch, "gets_per_arm": n_gets}
    mv.init([])
    try:
        table = mv.create_matrix_table(num_row, num_col)
        table.add_rows(stream[0], np.ones((stream[0].size, num_col),
                                          np.float32))
        for ids in stream[:40]:  # warm: compiles + buckets out of
            table.get_rows(ids)  # every timed window

        def arm(rate):
            set_flag("trace_sample_rate", rate)
            tracing.reset()
            rows = 0
            t0 = time.perf_counter()
            for ids in stream:
                table.get_rows(ids)
                rows += ids.size
            dt = time.perf_counter() - t0
            return rows / dt, len(tracing.snapshot_events())

        off, _ = arm(0.0)
        off2, _ = arm(0.0)       # repeat: the noise floor
        one_pct, ev1 = arm(0.01)
        full, ev100 = arm(1.0)

        # Structural off-path bound: the ONLY work the disabled layer
        # adds per request vs a pre-trace build is the sampling hook
        # (one flag read) + inert span checks; microbench the hook and
        # scale by the measured request rate.
        reps = 20000
        t0 = time.perf_counter()
        for _ in range(reps):
            tracing.new_trace(0)
        hook_ns = (time.perf_counter() - t0) / reps * 1e9
        # ~4 hook-class checks per get (issue + shard + reply + notify)
        off_bound = (hook_ns * 4e-9) * (off / per_batch)
    finally:
        # Flag restore is structural now (@flag_guarded).
        tracing.reset()
        mv.shutdown()
    out.update(
        off_rows_per_sec=round(off, 1),
        off_repeat_rows_per_sec=round(off2, 1),
        one_pct_rows_per_sec=round(one_pct, 1),
        full_rows_per_sec=round(full, 1),
        noise_floor=round(abs(off - off2) / max(off, off2), 4),
        overhead_one_pct=round(max(off, off2) / one_pct - 1, 4),
        overhead_full=round(max(off, off2) / full - 1, 4),
        events_at_one_pct=ev1, events_at_full=ev100,
        sampling_hook_ns=round(hook_ns, 1),
        off_overhead_bound=round(off_bound, 6),
        accept_off_overhead_le_1pct=bool(off_bound <= 0.01))
    return out


@flag_guarded
def run_serving() -> dict:
    """Serving-tier phase (docs/SERVING.md): Zipf(1.6) HTTP QPS
    against the online serving frontend while a trainer thread
    concurrently pushes Adds into the same table — the ROADMAP item 4
    'training + serving system' proof. Two arms over identical
    request streams:

    - NORMAL: default admission knobs; reports p50/p99 latency, QPS,
      rows/s, cache hit rate (request-level and row-granular, overall
      + on the Zipf head), shed rate (expected ~0), and
      staleness-bound violations (must be 0).
    - OVERLOAD: the per-endpoint in-flight cap is dropped to 1 and
      twice the client threads hammer with no backoff; the frontend
      must shed (429 + Retry-After on every shed) while the p99 of
      ACCEPTED requests stays bounded — load shedding IS the latency
      defense, so p99 must not collapse.

    Clients hold keep-alive connections (http.client over the
    frontend's HTTP/1.1) — the inference-client shape, and without it
    the TCP handshake per request IS the benchmark. Acceptance: head
    row-granular cache coverage >= 0.9 (the trainer deliberately
    dirties the head, so request-level all-rows-fresh hits are
    reported but not gated), every shed carries Retry-After, zero
    staleness violations, and overload p99 of accepted requests <=
    max(10x normal p99, 250 ms)."""
    import http.client
    import json
    import threading

    import multiverso_tpu as mv
    from multiverso_tpu.serving.frontend import ServingFrontend
    from multiverso_tpu.util.configure import set_flag

    num_row, num_col = 4096, 32
    staleness, head_n, per_req = 16, 16, 6
    out = {"num_row": num_row, "num_col": num_col,
           "max_get_staleness": staleness, "zipf_a": 1.6,
           "head_rows": head_n, "ids_per_request": per_req}

    mv.init([])
    set_flag("max_get_staleness", staleness)
    try:
        table = mv.create_matrix_table(num_row, num_col)
        rng = np.random.default_rng(5)
        table.add(rng.standard_normal((num_row, num_col))
                  .astype(np.float32))
        frontend = ServingFrontend(mv.current_zoo(), port=0,
                                   host="127.0.0.1")
        frontend.register_table("emb", table)

        stop = threading.Event()
        adds = [0]

        def trainer():
            """Concurrent write load: Zipf-shaped Adds (the word2vec
            delta pattern — head-heavy, so the trainer keeps dirtying
            exactly the rows users read most) with the idiomatic
            recovery prefetch of the dirtied rows
            (docs/CLIENT_CACHE.md)."""
            trng = np.random.default_rng(17)
            while not stop.is_set():
                ids = np.unique((trng.zipf(1.6, 16) - 1) % num_row) \
                    .astype(np.int32)
                table.add_rows(ids, np.full((ids.size, num_col), 1e-4,
                                            np.float32))
                table.prefetch_rows_async(ids)
                adds[0] += 1
                time.sleep(0.02)

        def _new_arm():
            return {"lock": threading.Lock(), "lat": [], "rows": 0,
                    "hits": 0, "misses": 0, "rows_req": 0,
                    "rows_cached": 0, "head_total": 0, "head_hits": 0,
                    "head_rows_req": 0, "head_rows_cached": 0,
                    "shed": 0, "shed_no_retry_after": 0,
                    "staleness_violations": 0}

        def client(seed, n, arm):
            """One keep-alive inference client: Zipf(1.6) row reads,
            sheds counted (and their Retry-After checked), accepted
            responses checked for the staleness invariant."""
            crng = np.random.default_rng(seed)
            conn = http.client.HTTPConnection("127.0.0.1",
                                              frontend.port,
                                              timeout=60)
            try:
                for _ in range(n):
                    ids = np.unique((crng.zipf(1.6, per_req) - 1)
                                    % num_row)
                    path = ("/v1/tables/emb/rows?ids="
                            + ",".join(str(i) for i in ids))
                    t0 = time.perf_counter()
                    conn.request("GET", path)
                    resp = conn.getresponse()
                    body = resp.read()  # always: keep-alive reuse
                    if resp.status in (429, 503):
                        with arm["lock"]:
                            arm["shed"] += 1
                            if resp.getheader("Retry-After") is None:
                                arm["shed_no_retry_after"] += 1
                        continue
                    assert resp.status == 200, (resp.status, body)
                    doc = json.loads(body)
                    lat_ms = (time.perf_counter() - t0) * 1e3
                    head = bool(ids.max() < head_n)
                    with arm["lock"]:
                        arm["lat"].append(lat_ms)
                        arm["rows"] += int(ids.size)
                        arm["hits" if doc["cache_hit"]
                            else "misses"] += 1
                        arm["rows_req"] += doc["rows_requested"]
                        arm["rows_cached"] += doc["rows_cached"]
                        if head:
                            arm["head_total"] += 1
                            arm["head_hits"] += int(doc["cache_hit"])
                            arm["head_rows_req"] += \
                                doc["rows_requested"]
                            arm["head_rows_cached"] += \
                                doc["rows_cached"]
                        if doc["max_staleness"] > \
                                doc["staleness_bound"]:
                            arm["staleness_violations"] += 1
            finally:
                conn.close()

        def run_arm(n_threads, n_per, seed0):
            arm = _new_arm()
            threads = [threading.Thread(target=client,
                                        args=(seed0 + i, n_per, arm))
                       for i in range(n_threads)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            lat = sorted(arm["lat"])

            def pick(p):
                return round(lat[min(int(len(lat) * p / 100),
                                     len(lat) - 1)], 3) if lat else None
            served = arm["hits"] + arm["misses"]
            total = served + arm["shed"]
            return {
                "requests": total, "served": served,
                "elapsed_s": round(elapsed, 3),
                "qps": round(total / elapsed, 1),
                "rows_per_s": round(arm["rows"] / elapsed, 1),
                "p50_ms": pick(50), "p99_ms": pick(99),
                "hit_rate": round(arm["hits"] / max(served, 1), 4),
                "row_hit_rate": round(
                    arm["rows_cached"] / max(arm["rows_req"], 1), 4),
                "head_requests": arm["head_total"],
                "head_hit_rate": round(
                    arm["head_hits"] / max(arm["head_total"], 1), 4),
                "head_row_hit_rate": round(
                    arm["head_rows_cached"]
                    / max(arm["head_rows_req"], 1), 4),
                "shed": arm["shed"],
                "shed_rate": round(arm["shed"] / max(total, 1), 4),
                "shed_without_retry_after":
                    arm["shed_no_retry_after"],
                "staleness_violations": arm["staleness_violations"]}

        trainer_thread = threading.Thread(target=trainer, daemon=True)
        trainer_thread.start()
        # Warm: gather-bucket compiles out of the timed window, cache
        # populated to steady state (the state a serving replica runs
        # in; cold-start is the client_cache phase's story).
        for k in (4, 8, 16, 32, 64):
            table.get_rows(np.linspace(0, num_row - 1, k)
                           .astype(np.int32))
        client(99, 120, _new_arm())

        normal = run_arm(n_threads=3, n_per=200, seed0=100)
        # Deliberate overload: one admitted request at a time, twice
        # the clients, zero client backoff. Restore whatever cap the
        # controller actually ran with (flag-sourced — a hand-copied
        # constant here would drift from the canonical default).
        prior_inflight = frontend.admission.stats()["max_inflight"]
        frontend.admission.configure(max_inflight=1)
        overload = run_arm(n_threads=6, n_per=100, seed0=200)
        frontend.admission.configure(max_inflight=prior_inflight)
        stop.set()
        trainer_thread.join(timeout=10)
        out["adds_during_run"] = adds[0]
        out["admission"] = frontend.admission.stats()
        drain_t0 = time.perf_counter()
        frontend.stop()
        out["drain_s"] = round(time.perf_counter() - drain_t0, 3)
    finally:
        # Flag restore is structural now (@flag_guarded).
        mv.shutdown()

    p99_bound_ms = max(10 * (normal["p99_ms"] or 0.0), 250.0)
    out.update(
        normal=normal, overload=overload,
        accept_head_hit_rate_ge_090=bool(
            normal["head_row_hit_rate"] >= 0.9),
        accept_overload_sheds=bool(overload["shed"] > 0),
        accept_sheds_carry_retry_after=bool(
            overload["shed_without_retry_after"] == 0
            and normal["shed_without_retry_after"] == 0),
        accept_zero_staleness_violations=bool(
            normal["staleness_violations"] == 0
            and overload["staleness_violations"] == 0),
        overload_p99_bound_ms=round(p99_bound_ms, 3),
        accept_overload_p99_accepted_bounded=bool(
            overload["p99_ms"] is not None
            and overload["p99_ms"] <= p99_bound_ms))
    return out


@flag_guarded
def run_autotune() -> dict:
    """Closed-loop self-tuning phase (docs/AUTOTUNE.md): the ps-matrix
    Zipf read/write workload and the HTTP serving workload, each run
    under three configurations over identical request streams:

    - DEFAULT: all-default flags, no controller — the baseline a
      fresh cluster starts from;
    - HAND-TUNED: the best known static configuration
      (-max_get_staleness=24, the client_cache/serving phases'
      tuning) pinned before table creation;
    - ADAPTIVE: all-default flags plus the controller
      (-metrics_interval_s + -autotune_interval_s): per-rank metric
      reports feed ClusterMetrics, the AutotuneManager's policies
      widen the knobs via epoch-stamped Control_Config broadcasts,
      and the dynamic-flag layer's apply hooks land them on the LIVE
      table and frontend.

    Correctness is checked WHILE the knobs move: a same-thread
    read-your-writes probe after every hot-row add (the served value
    must reflect the just-acked write exactly), and the serving
    staleness invariant on every response. Acceptance: the adaptive
    run converges to >= 0.95x the hand-tuned static configuration on
    both workloads with zero violations, and the decision trajectory
    (mv_autotune_*) is present in /metrics and recorded here."""
    import http.client
    import threading

    import multiverso_tpu as mv
    from multiverso_tpu.runtime import actor as actors
    from multiverso_tpu.serving.frontend import ServingFrontend
    from multiverso_tpu.util.configure import get_flag, set_flag

    num_row, num_col, per_batch = 1 << 14, 32, 192
    pool, hand_staleness = 64, 24
    rng = np.random.default_rng(23)
    ranks = np.arange(1, num_row + 1)
    probs = 1.0 / ranks  # Zipf(1.0) row popularity
    probs /= probs.sum()
    batches = [np.unique(rng.choice(num_row, size=per_batch,
                                    p=probs)).astype(np.int32)
               for _ in range(pool)]
    hot = np.unique(rng.choice(256, size=64)).astype(np.int32)
    # Init rows exclude the hot set so the RYW probe's expected value
    # is exactly the number of acked hot adds (all cols move by 1).
    init_rows = np.setdiff1d(batches[0], hot).astype(np.int32)

    def matrix_workload(table, seconds, adds_so_far, ryw):
        """TIME-BOUNDED Zipf Get stream with periodic hot-row adds
        riding along (the client_cache phase's shape). Time-bounded,
        not pass-bounded: one pass over the pool takes ~70 ms on this
        host, far inside its ±20% scheduler noise — a multi-second
        window averages it out, and keeps the metrics stream hot for
        the whole autotune decision cadence. After every acked add the
        SAME THREAD re-reads a hot-row slice and checks the value
        reflects the write exactly — read-your-writes must hold at
        whatever staleness bound is live. Returns (rows/s, adds)."""
        rows = 0
        i = 0
        t0 = time.perf_counter()
        deadline = t0 + seconds
        while time.perf_counter() < deadline:
            table.get_rows(batches[i % pool])
            rows += batches[i % pool].size
            i += 1
            if i % 24 == 0:
                table.add_rows(hot, np.ones((hot.size, num_col),
                                            np.float32))
                adds_so_far += 1
                probe = table.get_rows(hot[:8])
                if not np.allclose(probe, float(adds_so_far)):
                    ryw[0] += 1
                table.prefetch_rows_async(hot)
        return rows / (time.perf_counter() - t0), adds_so_far

    def serving_workload(port, n_threads, n_per, seed0):
        """Keep-alive Zipf(1.6) HTTP clients against /rows; returns
        qps / p99 / staleness violations / request-level hit rate."""
        lock = threading.Lock()
        acc = {"lat": [], "hits": 0, "served": 0, "violations": 0,
               "shed": 0}

        def client(seed, n):
            crng = np.random.default_rng(seed)
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            try:
                for _ in range(n):
                    ids = np.unique((crng.zipf(1.6, 6) - 1) % num_row)
                    path = ("/v1/tables/emb/rows?ids="
                            + ",".join(str(i) for i in ids))
                    t0 = time.perf_counter()
                    conn.request("GET", path)
                    resp = conn.getresponse()
                    body = resp.read()
                    if resp.status in (429, 503):
                        with lock:
                            acc["shed"] += 1
                        continue
                    assert resp.status == 200, (resp.status, body)
                    doc = json.loads(body)
                    lat = (time.perf_counter() - t0) * 1e3
                    with lock:
                        acc["lat"].append(lat)
                        acc["served"] += 1
                        acc["hits"] += int(bool(doc["cache_hit"]))
                        if doc["max_staleness"] > \
                                doc["staleness_bound"]:
                            acc["violations"] += 1
            finally:
                conn.close()

        threads = [threading.Thread(target=client,
                                    args=(seed0 + i, n_per))
                   for i in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        lat = sorted(acc["lat"])
        return {
            "qps": round((acc["served"] + acc["shed"]) / elapsed, 1),
            "p50_ms": round(lat[len(lat) // 2], 3) if lat else None,
            "p99_ms": round(lat[min(int(len(lat) * 0.99),
                                    len(lat) - 1)], 3) if lat else None,
            "hit_rate": round(acc["hits"] / max(acc["served"], 1), 4),
            "shed": acc["shed"],
            "staleness_violations": acc["violations"]}

    def run_arm(static_flags, autotune):
        """One full configuration: matrix workload then serving
        workload in a single cluster lifetime, all flags restored on
        exit (flag_guard)."""
        arm = {}
        with flag_guard():
            for k, v in static_flags.items():
                set_flag(k, v)
            if autotune:
                set_flag("metrics_interval_s", 0.2)
                set_flag("autotune_interval_s", 0.3)
            mv.init([])
            try:
                zoo = mv.current_zoo()
                table = mv.create_matrix_table(num_row, num_col)
                table.add_rows(init_rows,
                               np.ones((init_rows.size, num_col),
                                       np.float32))
                ryw = [0]
                adds = 0
                for ids in batches:  # warm: compiles + buckets out of
                    table.get_rows(ids)  # every timed window
                if autotune:
                    # Convergence window (untimed): keep the workload
                    # hot while the controller widens the knobs from
                    # live ClusterMetrics. Settled = the staleness
                    # policy VERDICT reads "hold" at a nonzero bound
                    # for two consecutive passes — i.e. the controller
                    # itself judges the knob at its operating point
                    # (miss rate absorbed), not merely between
                    # cooldown steps. An intermediate bound is the
                    # worst regime (cache bookkeeping with no hits),
                    # so timing before the verdict settles would
                    # measure the transition, not the steady state.
                    mgr = zoo._actors[actors.CONTROLLER].autotune
                    deadline = time.monotonic() + 30.0
                    settled = 0
                    while time.monotonic() < deadline and settled < 2:
                        _, adds = matrix_workload(table, 1.0, adds,
                                                  ryw)
                        gauge = mgr.gauges().get(
                            "max_get_staleness", {})
                        # "hold" = the POLICY judged the knob at its
                        # operating point under live traffic ("idle"
                        # windows don't count; "up"/"down" means
                        # still stepping or cooling down).
                        held = (gauge.get("verdict") == "hold"
                                and get_flag("max_get_staleness") > 0)
                        settled = settled + 1 if held else 0
                    arm["converged_staleness"] = int(
                        get_flag("max_get_staleness"))
                matrix_rows_s, adds = matrix_workload(table, 4.0,
                                                      adds, ryw)
                arm["matrix_rows_per_s"] = round(matrix_rows_s, 1)
                arm["ryw_violations"] = ryw[0]

                frontend = ServingFrontend(zoo, port=0,
                                           host="127.0.0.1")
                frontend.register_table("emb", table)
                stop = threading.Event()

                def trainer():
                    trng = np.random.default_rng(17)
                    while not stop.is_set():
                        ids = np.unique((trng.zipf(1.6, 16) - 1)
                                        % num_row).astype(np.int32)
                        table.add_rows(
                            ids, np.full((ids.size, num_col), 1e-4,
                                         np.float32))
                        table.prefetch_rows_async(ids)
                        time.sleep(0.02)

                trainer_thread = threading.Thread(target=trainer,
                                                  daemon=True)
                trainer_thread.start()
                serving_workload(frontend.port, 1, 60, 900)  # warm
                arm["serving"] = serving_workload(
                    frontend.port, 3, 250, 1000)
                stop.set()
                trainer_thread.join(timeout=10)

                if autotune:
                    controller = zoo._actors.get(actors.CONTROLLER)
                    mgr = controller.autotune
                    arm["trajectory"] = mgr.trajectory()
                    arm["gauges"] = mgr.gauges()
                    arm["config_epoch"] = mgr.epoch
                    arm["acked_epochs"] = {
                        str(r): e
                        for r, e in mgr.acked_epochs().items()}
                    arm["final_knobs"] = {
                        k: get_flag(k)
                        for k in ("max_get_staleness",
                                  "serving_batch_window_ms",
                                  "coalesce_max_msgs")}
                    # Scrape-surface proof: the EXACT /metrics
                    # composition the zoo serves on -metrics_port,
                    # fetched over real HTTP (ephemeral port).
                    from multiverso_tpu.io.metrics_http import (
                        MetricsHttpServer, prometheus_route)
                    scrape = MetricsHttpServer(0, {
                        "/metrics": prometheus_route(
                            lambda: controller.metrics
                            .prometheus_text()
                            + mgr.prometheus_text())},
                        host="127.0.0.1")
                    try:
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", scrape.port, timeout=10)
                        conn.request("GET", "/metrics")
                        text = conn.getresponse().read().decode()
                        conn.close()
                    finally:
                        scrape.stop()
                    arm["metrics_scrape"] = {
                        "autotune_gauge_lines": sum(
                            1 for line in text.splitlines()
                            if line.startswith("mv_autotune_")),
                        "has_config_epoch":
                            "mv_autotune_config_epoch" in text,
                        "has_knob_values":
                            'mv_autotune_value{knob=' in text}
                frontend.stop()
            finally:
                mv.shutdown()
        return arm

    out = {"num_row": num_row, "num_col": num_col,
           "rows_per_get": per_batch, "batch_pool": pool,
           "hand_tuned_staleness": hand_staleness}
    out["default_static"] = run_arm({}, autotune=False)
    out["hand_tuned"] = run_arm(
        {"max_get_staleness": hand_staleness}, autotune=False)
    out["adaptive"] = run_arm({}, autotune=True)

    tuned, adaptive = out["hand_tuned"], out["adaptive"]
    out.update(
        adaptive_vs_hand_tuned_matrix=round(
            adaptive["matrix_rows_per_s"]
            / max(tuned["matrix_rows_per_s"], 1e-9), 3),
        adaptive_vs_hand_tuned_qps=round(
            adaptive["serving"]["qps"]
            / max(tuned["serving"]["qps"], 1e-9), 3),
        adaptive_vs_default_matrix=round(
            adaptive["matrix_rows_per_s"]
            / max(out["default_static"]["matrix_rows_per_s"], 1e-9),
            3),
        accept_matrix_ge_095x_hand_tuned=bool(
            adaptive["matrix_rows_per_s"]
            >= 0.95 * tuned["matrix_rows_per_s"]),
        accept_qps_ge_095x_hand_tuned=bool(
            adaptive["serving"]["qps"]
            >= 0.95 * tuned["serving"]["qps"]),
        accept_zero_violations_while_tuning=bool(
            adaptive["ryw_violations"] == 0
            and adaptive["serving"]["staleness_violations"] == 0),
        accept_trajectory_in_metrics=bool(
            len(adaptive.get("trajectory") or []) > 0
            and adaptive["metrics_scrape"]["has_config_epoch"]
            and adaptive["metrics_scrape"]["has_knob_values"]))
    return out


_FLEET_CHILD = '''
import sys, threading, time
sys.path.insert(0, {repo!r})
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import multiverso_tpu as mv

rank, n = int(sys.argv[1]), int(sys.argv[2])
role, serving_port = sys.argv[3], int(sys.argv[4])
argv = ["-machine_file=" + {mf!r}, "-rank=" + str(rank),
        "-ps_role=" + role, "-serving_fleet_interval_s=0.5"]
argv += list(sys.argv[5:])  # arm-specific flags from the parent
if serving_port:
    argv.append("-serving_port=" + str(serving_port))
mv.init(argv)
NUM_ROW, NUM_COL = {num_row}, {num_col}
table = mv.create_matrix_table(NUM_ROW, NUM_COL)
if table is not None:
    if rank == 1:
        # Deterministic integer-valued base: the parent recomputes it
        # and verifies every served row against the legal-value rule
        # (cols 1+ untouched, col 0 = base + integer add count).
        base = (np.arange(NUM_ROW)[:, None] % 50
                + np.arange(NUM_COL)[None, :]).astype(np.float32)
        table.add_rows(np.arange(NUM_ROW, dtype=np.int32), base)
    mv.barrier()
    mv.serve_table("emb", table)
    # Warm the gather buckets out of the measured window (requests
    # carry up to ~8 unique rows -> power-of-two buckets 1..16, and
    # the scatter path splits per owner, so small widths occur too).
    for k in (1, 2, 3, 4, 6, 8, 12, 16):
        table.get_rows(np.linspace(0, NUM_ROW - 1, k)
                       .astype(np.int32))
    stop = threading.Event()
    adds = [0]

    def trainer():
        rng = np.random.default_rng(100 + rank)
        while not stop.is_set():
            ids = np.unique((rng.zipf(1.6, 8) - 1)
                            % NUM_ROW).astype(np.int32)
            delta = np.zeros((ids.size, NUM_COL), np.float32)
            delta[:, 0] = 1.0
            table.add_rows(ids, delta)
            adds[0] += 1
            time.sleep(0.02)

    t = threading.Thread(target=trainer, daemon=True)
    t.start()
    print("READY", serving_port, flush=True)
    while True:
        line = sys.stdin.readline()
        if line.startswith("SAMPLE"):
            # Self-reported thread census for the many-connection arm
            # (Python 3.10 does not propagate thread names to /proc
            # comm, so the parent cannot count roles from outside).
            from multiverso_tpu.runtime import thread_roles as tr
            alive = tr.roles_alive()
            print("THREADS", threading.active_count(),
                  alive.get(tr.EVENTLOOP, 0) + alive.get(tr.WRITER, 0),
                  flush=True)
            continue
        break
    stop.set()
    t.join(timeout=10)
    print("ADDS", adds[0], flush=True)
else:
    mv.barrier()
    print("READY 0", flush=True)
    sys.stdin.readline()
mv.shutdown()
print("DONE", flush=True)
'''


_FLEET_CLIENT = '''
import json, sys, time
import http.client
import numpy as np

port, seed, n_reqs = (int(v) for v in sys.argv[1:4])
ids_per_req, zipf_a = int(sys.argv[4]), float(sys.argv[5])
NUM_ROW, NUM_COL = {num_row}, {num_col}
base = (np.arange(NUM_ROW)[:, None] % 50
        + np.arange(NUM_COL)[None, :]).astype(np.float32)
crng = np.random.default_rng(seed)
out = {{"lat": [], "served": 0, "shed": 0,
       "staleness_violations": 0, "wrong_values": 0, "hits": 0,
       "rows_req": 0, "rows_cached": 0, "response_cache_hits": 0,
       "errors": []}}
conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
t_start = time.perf_counter()
for _ in range(n_reqs):
    ids = np.unique((crng.zipf(zipf_a, ids_per_req) - 1) % NUM_ROW)
    path = "/v1/tables/emb/rows?ids=" \\
        + ",".join(str(i) for i in ids)
    t0 = time.perf_counter()
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    if resp.status in (429, 503):
        out["shed"] += 1
        continue
    if resp.status != 200:
        out["errors"].append([resp.status, body[:200].decode(
            errors="replace")])
        continue
    doc = json.loads(body)
    out["lat"].append((time.perf_counter() - t0) * 1e3)
    out["served"] += 1
    # Legal-value rule: cols 1+ untouched by the trainer, col 0 =
    # base + integer add count. A stale/torn/misrouted row cannot
    # pass.
    for row_id, row in zip(doc["ids"], doc["rows"]):
        row = np.asarray(row, np.float64)
        if not np.array_equal(row[1:], base[row_id][1:]):
            out["wrong_values"] += 1
            continue
        delta = row[0] - base[row_id][0]
        if delta < -1e-6 or abs(delta - round(delta)) > 1e-3:
            out["wrong_values"] += 1
    out["hits"] += int(bool(doc["cache_hit"]))
    out["rows_req"] += doc["rows_requested"]
    out["rows_cached"] += doc["rows_cached"]
    out["response_cache_hits"] += int(
        doc.get("response_cache") == "hit")
    if doc["max_staleness"] > doc["staleness_bound"]:
        out["staleness_violations"] += 1
out["elapsed"] = time.perf_counter() - t_start
conn.close()
print("CLIENTRES " + json.dumps(out), flush=True)
'''


def _fleet_sweep_arm(n_frontends: int, tmp: str, num_row: int = 4096,
                     num_col: int = 32, clients: int = 8,
                     reqs_per_client: int = 150,
                     child_flags=("-max_get_staleness=16",),
                     ids_per_req: int = 6, zipf_a: float = 1.6,
                     label: str = "") -> dict:
    """One multi-process fleet point: rank 0 = server + controller,
    ranks 1..N = worker frontends (each its own OS process and GIL —
    the real fleet shape). The HTTP clients are their OWN processes
    too (one synchronous keep-alive connection each, spread across
    the frontends), so the measurement is never capped by a shared
    client-side GIL; every response is checked for the staleness
    invariant AND the legal-value rule (cols 1+ must equal the
    deterministic base exactly; col 0 must exceed it by a
    non-negative INTEGER — the trainer only ever adds +1.0 there), so
    a torn/stale/misrouted row can never pass."""
    from multiverso_tpu.util.net_util import free_listen_port

    n = n_frontends + 1
    mf = os.path.join(tmp, f"fleet_mf_{n_frontends}{label}.txt")
    with open(mf, "w") as f:
        for p in [free_listen_port() for _ in range(n)]:
            f.write(f"127.0.0.1:{p}\n")
    serving_ports = [free_listen_port() for _ in range(n_frontends)]
    code = _FLEET_CHILD.format(
        repo=os.path.dirname(os.path.abspath(__file__)), mf=mf,
        num_row=num_row, num_col=num_col)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    for rank in range(n):
        role = "server" if rank == 0 else "worker"
        port = 0 if rank == 0 else serving_ports[rank - 1]
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code, str(rank), str(n),
             role, str(port), *child_flags],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env))
    client_code = _FLEET_CLIENT.format(num_row=num_row,
                                       num_col=num_col)

    fleet_doc = None
    try:
        for p in procs:  # all ranks up and serving; log INFO lines
            while True:  # share the pipe with the READY marker
                line = p.stdout.readline()
                if not line:
                    # Child died before READY; stderr is safe to
                    # drain only because the process has exited.
                    p.wait(timeout=30)
                    raise RuntimeError(
                        f"fleet child exited rc={p.returncode}: "
                        f"{p.stderr.read()[-400:]}")
                if line.startswith("READY"):
                    break
        client_procs = []
        t0 = time.perf_counter()
        for i in range(clients):
            port = serving_ports[i % n_frontends]
            client_procs.append(subprocess.Popen(
                [sys.executable, "-c", client_code, str(port),
                 str(1000 + i), str(reqs_per_client),
                 str(ids_per_req), str(zipf_a)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env))
        stats = {"lat": [], "served": 0, "shed": 0,
                 "staleness_violations": 0, "wrong_values": 0,
                 "hits": 0, "rows_req": 0, "rows_cached": 0,
                 "response_cache_hits": 0, "errors": [],
                 "client_qps": []}
        for p in client_procs:
            out, err = p.communicate(timeout=600)
            if p.returncode:
                raise RuntimeError(
                    f"fleet client failed: {err[-400:]}")
            doc = None
            for line in out.splitlines():
                if line.startswith("CLIENTRES "):
                    doc = json.loads(line[10:])
            if doc is None:
                raise RuntimeError(
                    f"fleet client printed no result: {out[-200:]}")
            stats["lat"].extend(doc.pop("lat"))
            stats["errors"].extend(doc.pop("errors"))
            # Per-client rate over the client's OWN request window
            # (excludes interpreter startup; clients run concurrently,
            # so the aggregate is the sum of rates).
            client_elapsed = doc.pop("elapsed")
            stats["client_qps"].append(
                (doc["served"] + doc["shed"])
                / max(client_elapsed, 1e-9))
            for key, value in doc.items():
                stats[key] += value
        elapsed = time.perf_counter() - t0
        # The fleet view any load balancer would scrape, from the
        # FIRST frontend (all frontends converge on the aggregate).
        try:
            import urllib.request
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{serving_ports[0]}/v1/status",
                    timeout=10) as resp:
                fleet_doc = json.loads(resp.read()).get("fleet")
        except Exception:  # noqa: BLE001 - observability only
            fleet_doc = None
    finally:
        for p in procs:
            try:
                p.stdin.write("\n")
                p.stdin.flush()
            except Exception:  # noqa: BLE001
                pass
        for p in procs:
            try:
                p.communicate(timeout=120)
            except Exception:  # noqa: BLE001
                p.kill()
                p.communicate()
    lat = sorted(stats["lat"])

    def pick(p):
        return round(lat[min(int(len(lat) * p / 100),
                             len(lat) - 1)], 3) if lat else None

    total = stats["served"] + stats["shed"]
    return {
        "frontends": n_frontends, "clients": clients,
        "requests": total, "served": stats["served"],
        "elapsed_s": round(elapsed, 3),
        "aggregate_qps": round(sum(stats["client_qps"]), 1),
        "p50_ms": pick(50), "p99_ms": pick(99),
        "hit_rate": round(stats["hits"] / max(stats["served"], 1), 4),
        "row_hit_rate": round(stats["rows_cached"]
                              / max(stats["rows_req"], 1), 4),
        "response_cache_hit_rate": round(
            stats["response_cache_hits"]
            / max(stats["served"], 1), 4),
        "shed": stats["shed"],
        "staleness_violations": stats["staleness_violations"],
        "wrong_values": stats["wrong_values"],
        "http_errors": stats["errors"][:5],
        "fleet_view": fleet_doc}


def _ann_arm(num_row: int = 131072, num_col: int = 64,
             n_queries: int = 200, k: int = 10) -> dict:
    """IVF vs the linear scan on an embedding-shaped (clustered)
    table: measured recall@10 against the exact brute ranking and the
    per-query speedup. Pure host compute — exactly what the neighbors
    endpoint runs per request on its snapshot."""
    from multiverso_tpu.serving.ann import IVFIndex

    rng = np.random.default_rng(7)
    n_clusters = 256
    centers = rng.standard_normal((n_clusters, num_col)) \
        .astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1)[:, None]
    values = (centers[rng.integers(0, n_clusters, num_row)]
              + 0.08 * rng.standard_normal((num_row, num_col))
              .astype(np.float32)).astype(np.float32)
    norms = np.maximum(np.linalg.norm(values, axis=1), 1e-12)
    # Past sqrt(N) toward smaller lists: per-query cost follows
    # nprobe x N / nlist candidate rows, and on well-clustered
    # embedding data recall holds at small nprobe (measured below,
    # not assumed).
    nlist = 512
    nprobe = 4
    t0 = time.perf_counter()
    index = IVFIndex(values, norms, nlist=nlist)
    build_s = time.perf_counter() - t0
    queries = rng.integers(0, num_row, n_queries)

    def brute(row):
        q = values[row]
        scores = (values @ q) / (norms * max(np.linalg.norm(q),
                                             1e-12))
        scores[row] = -np.inf
        top = np.argpartition(-scores, k)[:k]
        return top[np.argsort(-scores[top])]

    t0 = time.perf_counter()
    exact = [brute(int(r)) for r in queries]
    brute_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    approx = [index.search(values[int(r)], k, nprobe,
                           exclude=int(r))[0] for r in queries]
    ivf_s = time.perf_counter() - t0
    recall = float(np.mean(
        [len(set(map(int, e)) & set(map(int, a))) / k
         for e, a in zip(exact, approx)]))
    return {
        "num_row": num_row, "num_col": num_col, "nlist": nlist,
        "nprobe": nprobe, "queries": n_queries,
        "build_s": round(build_s, 3),
        "brute_ms_per_query": round(brute_s / n_queries * 1e3, 4),
        "ivf_ms_per_query": round(ivf_s / n_queries * 1e3, 4),
        "speedup": round(brute_s / ivf_s, 2),
        "recall_at_10": round(recall, 4)}


def _batching_arm(tmp: str) -> dict:
    """Batched scatter reads vs the serialized per-request gather
    path, A/B over identical load shape: a 2-process TCP cluster
    (worker+frontend process, server process) on a paced 1 Mbps
    emulated expensive-roundtrip link (the PR-7 pacing convention,
    turned down so the backend roundtrip — not frontend CPU — is the
    dominant cost, the regime the real tunneled-device platform lives
    in, where one dispatch roundtrip costs ~92 ms), client cache and
    hot-response cache OFF so every request really crosses the wire.
    8 concurrent keep-alive clients, Zipf(2.0) multi-row reads
    (the hot-head read regime ISSUE/ROADMAP motivate batching with),
    trainer running throughout.

    The legacy arm (-serving_scatter=false) serializes requests on
    the table's one-get-in-flight registers: 8 clients queue behind
    one paced roundtrip per request. The batched arm folds the
    concurrent requests of each -serving_batch_window_ms window into
    ONE merged read — one roundtrip (and one device gather per
    shard) per BATCH, with the Zipf head deduplicated across the
    folded requests (~2x fewer unique rows than the per-request sum
    at this skew), so both the fixed roundtrip AND the paced bytes
    amortize over the batch."""
    common = ("-max_get_staleness=0", "-serving_hot_rows=0",
              "-net_pace_mbps=1")
    per_request = _fleet_sweep_arm(
        1, tmp, clients=8, reqs_per_client=100, zipf_a=2.0,
        child_flags=common + ("-serving_scatter=false",),
        label="_ab_legacy")
    batched = _fleet_sweep_arm(
        1, tmp, clients=8, reqs_per_client=100, zipf_a=2.0,
        child_flags=common + ("-serving_batch_window_ms=3",),
        label="_ab_batched")
    return {
        "clients": 8, "pace_mbps": 1, "zipf_a": 2.0,
        "per_request": per_request, "batched": batched,
        "batched_vs_per_request": round(
            batched["aggregate_qps"]
            / max(per_request["aggregate_qps"], 1e-9), 3)}


def run_serving_fleet(tmp: str) -> dict:
    """Serving-fleet phase (docs/SERVING.md fleet section): the
    multi-rank read path measured end to end.

    - ANN: IVF vs the linear scan on a 32k-row clustered table —
      acceptance >= 5x per-query speedup at recall@10 >= 0.95.
    - BATCHING: batched scatter reads vs the serialized per-request
      gather path under 8 concurrent clients — acceptance >= 2x QPS.
    - FLEET SWEEP: 1 vs 2 frontend PROCESSES over a shared server
      rank (TCP machine-file mesh), training concurrent, parent-side
      clients verifying every response's staleness bound and legal
      value — acceptance: 2 frontends >= 1.5x aggregate QPS with p99
      within the shared bound, 0 staleness violations, 0 wrong
      values across ALL arms."""
    out = {"ann": _ann_arm(), "batching": _batching_arm(tmp)}
    sweep = {}
    for n_frontends in (1, 2):
        # 24 clients saturate one frontend process (the GIL is the
        # per-frontend capacity on this host): without queueing at
        # the single frontend there is nothing for the second one to
        # relieve and the ratio just measures latency, not capacity.
        sweep[f"f{n_frontends}"] = _fleet_sweep_arm(
            n_frontends, tmp, clients=24, reqs_per_client=250)
    out["sweep"] = sweep
    f1, f2 = sweep["f1"], sweep["f2"]
    # Equal p99 bound for both sweep arms: generous vs the
    # single-frontend measurement, floored against timer noise.
    p99_bound_ms = max(3.0 * (f1["p99_ms"] or 0.0), 50.0)
    out.update(
        p99_bound_ms=round(p99_bound_ms, 3),
        fleet_qps_ratio=round(
            f2["aggregate_qps"] / max(f1["aggregate_qps"], 1e-9), 3),
        accept_ann_5x_at_recall_095=bool(
            out["ann"]["speedup"] >= 5.0
            and out["ann"]["recall_at_10"] >= 0.95),
        accept_batched_2x=bool(
            out["batching"]["batched_vs_per_request"] >= 2.0),
        accept_two_frontends_150=bool(
            f2["aggregate_qps"] >= 1.5 * f1["aggregate_qps"]
            and (f1["p99_ms"] or 1e9) <= p99_bound_ms
            and (f2["p99_ms"] or 1e9) <= p99_bound_ms),
        accept_zero_staleness_violations=bool(
            f1["staleness_violations"] == 0
            and f2["staleness_violations"] == 0
            and out["batching"]["per_request"]
                   ["staleness_violations"] == 0
            and out["batching"]["batched"]
                   ["staleness_violations"] == 0),
        accept_zero_wrong_values=bool(
            f1["wrong_values"] == 0 and f2["wrong_values"] == 0
            and out["batching"]["per_request"]["wrong_values"] == 0
            and out["batching"]["batched"]["wrong_values"] == 0))
    return out


_MANYCONN_CLIENT = '''
import json, os, socket, sys, time
import selectors

port, n_conns, reqs_per_conn, window = (int(v) for v in sys.argv[1:5])
REQ = (b"GET /v1/tables/emb/rows?ids=1,5,9,13 HTTP/1.1\\r\\n"
       b"Host: 127.0.0.1\\r\\nConnection: keep-alive\\r\\n\\r\\n")

# Phase 1: establish every keep-alive connection up front (sequential
# blocking dials on loopback are ~0.1 ms each and never overflow the
# accept backlog). The pump itself is ONE thread + one selector.
socks = []
for _ in range(n_conns):
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    s.setblocking(False)
    socks.append(s)
fd_count = len(os.listdir("/proc/self/fd"))
print("CONNECTED", len(socks), fd_count, flush=True)
sys.stdin.readline()  # parent samples the frontend /proc, then acks

# Phase 2: single-threaded selectors pump. Each connection answers
# reqs_per_conn requests; at most `window` are in flight at once so
# the other ~500 connections sit ESTABLISHED-idle — the C10k shape the
# event-loop transport exists for. One request outstanding per
# connection, so a read buffer never holds more than one response.
sel = selectors.DefaultSelector()
state = {}  # sock -> [buf, t0, remaining]
for s in socks:
    state[s] = [b"", 0.0, reqs_per_conn]
idle = list(socks)
out = {"lat": [], "served": 0, "shed": 0, "errors": 0, "inflight_window": window}
total = n_conns * reqs_per_conn
done = 0
inflight = 0
t_start = time.perf_counter()
deadline = t_start + 600
while done < total and time.perf_counter() < deadline:
    while idle and inflight < window:
        s = idle.pop()
        st = state[s]
        st[0] = b""
        st[1] = time.perf_counter()
        assert s.send(REQ) == len(REQ)  # 80 B into an empty buffer
        sel.register(s, selectors.EVENT_READ)
        inflight += 1
    for key, _ in sel.select(timeout=10):
        s = key.fileobj
        st = state[s]
        try:
            data = s.recv(65536)
        except BlockingIOError:
            continue
        if not data:  # server hung up mid-exchange
            sel.unregister(s)
            s.close()
            st[2] = 0
            done += 1
            inflight -= 1
            out["errors"] += 1
            continue
        st[0] += data
        head_end = st[0].find(b"\\r\\n\\r\\n")
        if head_end < 0:
            continue
        head = st[0][:head_end].decode("latin-1")
        clen = 0
        for line in head.split("\\r\\n")[1:]:
            if line.lower().startswith("content-length:"):
                clen = int(line.split(":", 1)[1])
        if len(st[0]) < head_end + 4 + clen:
            continue
        status = int(head.split(None, 2)[1])
        if status == 200:
            out["lat"].append((time.perf_counter() - st[1]) * 1e3)
            out["served"] += 1
        elif status in (429, 503):
            out["shed"] += 1
        else:
            out["errors"] += 1
        sel.unregister(s)
        done += 1
        inflight -= 1
        st[2] -= 1
        if st[2] > 0:
            idle.append(s)
out["elapsed"] = time.perf_counter() - t_start
out["completed"] = done
out["total"] = total
for s in socks:
    s.close()
print("CLIENTRES " + json.dumps(out), flush=True)
'''


def run_many_connections(tmp: str, n_conns: int = 512,
                         reqs_per_conn: int = 4,
                         window: int = 48) -> dict:
    """Many-connection arm (docs/THREADS.md event-loop core): >= 512
    keep-alive HTTP clients held open against ONE frontend rank by a
    single-threaded selectors pump, with a bounded in-flight window so
    most connections sit established-idle — the C10k shape. Records
    QPS and p99 over the served requests plus the frontend's fd count
    and TRANSPORT thread count sampled from /proc while every
    connection is up. Acceptance: all n_conns connections concurrently
    established, and transport threads O(1) — the selector loop plus
    the (peer-count-bounded, connection-count-independent) shm ring
    writers — while total fds scale with connections."""
    from multiverso_tpu.util.net_util import free_listen_port

    mf = os.path.join(tmp, "manyconn_mf.txt")
    with open(mf, "w") as f:
        for p in (free_listen_port(), free_listen_port()):
            f.write(f"127.0.0.1:{p}\n")
    serving_port = free_listen_port()
    code = _FLEET_CHILD.format(
        repo=os.path.dirname(os.path.abspath(__file__)), mf=mf,
        num_row=4096, num_col=32)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    for rank, role, port in ((0, "server", 0),
                             (1, "worker", serving_port)):
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code, str(rank), "2", role,
             str(port), "-max_get_staleness=16"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env))
    out = {"n_conns": n_conns, "reqs_per_conn": reqs_per_conn}
    try:
        for p in procs:
            while True:
                line = p.stdout.readline()
                if not line:
                    p.wait(timeout=30)
                    raise RuntimeError(
                        f"manyconn child exited rc={p.returncode}: "
                        f"{p.stderr.read()[-400:]}")
                if line.startswith("READY"):
                    break
        client = subprocess.Popen(
            [sys.executable, "-c", _MANYCONN_CLIENT,
             str(serving_port), str(n_conns), str(reqs_per_conn),
             str(window)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env)
        try:
            line = client.stdout.readline()
            if not line.startswith("CONNECTED"):
                raise RuntimeError(
                    f"manyconn client failed to connect: "
                    f"{client.stderr.read()[-400:]}")
            _, connected, client_fds = line.split()
            out["connected"] = int(connected)
            out["client_fd_count"] = int(client_fds)
            # Every connection is established and held right now —
            # fd census from /proc, thread census self-reported by the
            # frontend over its stdin/stdout pipe (thread ROLES are
            # not visible from outside the process).
            fe = procs[1]
            try:
                out["frontend_fd_count"] = len(
                    os.listdir(f"/proc/{fe.pid}/fd"))
            except OSError:
                out["frontend_fd_count"] = None
            fe.stdin.write("SAMPLE\n")
            fe.stdin.flush()
            out["frontend_threads_total"] = None
            out["frontend_transport_threads"] = None
            while True:
                line = fe.stdout.readline()
                if not line:
                    break
                if line.startswith("THREADS"):
                    _, total, transport = line.split()
                    out["frontend_threads_total"] = int(total)
                    out["frontend_transport_threads"] = int(transport)
                    break
            client.stdin.write("\n")
            client.stdin.flush()
            cout, cerr = client.communicate(timeout=700)
        except Exception:
            client.kill()
            client.communicate()
            raise
        if client.returncode:
            raise RuntimeError(f"manyconn client failed: {cerr[-400:]}")
        doc = None
        for line in cout.splitlines():
            if line.startswith("CLIENTRES "):
                doc = json.loads(line[10:])
        if doc is None:
            raise RuntimeError(
                f"manyconn client printed no result: {cout[-200:]}")
    finally:
        for p in procs:
            try:
                p.stdin.write("\n")
                p.stdin.flush()
            except Exception:  # noqa: BLE001
                pass
        for p in procs:
            try:
                p.communicate(timeout=120)
            except Exception:  # noqa: BLE001
                p.kill()
                p.communicate()
    lat = sorted(doc.pop("lat"))

    def pick(p):
        return round(lat[min(int(len(lat) * p / 100),
                             len(lat) - 1)], 3) if lat else None

    out.update(
        served=doc["served"], shed=doc["shed"],
        errors=doc["errors"], completed=doc["completed"],
        elapsed_s=round(doc["elapsed"], 3),
        qps=round(doc["completed"] / max(doc["elapsed"], 1e-9), 1),
        p50_ms=pick(50), p99_ms=pick(99),
        inflight_window=doc["inflight_window"],
        accept_512_keepalive_connections=bool(
            out["connected"] >= 512
            and (out["frontend_fd_count"] or 0) >= 512),
        # O(1): one selector loop + at most one shm ring writer per
        # CO-LOCATED RANK (here: 1), never a thread per connection.
        accept_o1_transport_threads=bool(
            out["frontend_transport_threads"] is not None
            and out["frontend_transport_threads"] <= 4))
    return out


def matrix_bandwidth() -> dict:
    import jax.numpy as jnp

    import multiverso_tpu as mv
    from multiverso_tpu.updater import AddOption

    num_row, num_col, iters = 1_000_000, 50, 10
    nbytes = num_row * num_col * 4
    import jax

    # NOTE on timing: jax.block_until_ready is NOT reliable on the
    # tunneled platform (it can return before execution completes), so
    # every measurement below forces completion with a tiny scalar
    # READBACK chained onto the measured work.
    mv.init([])
    table = mv.create_matrix_table(num_row, num_col)
    delta = jnp.ones((num_row, num_col), jnp.float32)
    float(delta[0, 0])  # settle the upload
    table.add(delta)
    float(table.get_device()[0, 0])  # compile + settle
    start = time.perf_counter()
    ids = [table.add_async(delta) for _ in range(iters)]
    for msg_id in ids:
        table.wait(msg_id)
    float(table.get_device()[0, 0])  # the adds chain through the table
    add_gbps = nbytes / ((time.perf_counter() - start) / (iters + 1)) / 1e9
    start = time.perf_counter()
    acc = None
    for _ in range(iters):
        probe_elt = table.get_device()[0, 0]  # ties each get into the
        acc = probe_elt if acc is None else acc + probe_elt  # readback
    float(acc)
    get_gbps = nbytes / ((time.perf_counter() - start) / iters) / 1e9

    # Tunnel characterization (shared helpers with the start-of-run
    # weather_probe, so the two snapshots stay comparable): transfer
    # rates both directions — the host-buffer dirty Get is capped by
    # them, not by the table stack; the per-call dispatch floor; and
    # the per-PROGRAM launch floor sampled as a small DISTRIBUTION
    # (the overhead is weather-volatile 5-50x over hours and a single
    # mean hides that).
    up_mbps, down_mbps = _tunnel_rates_mbps(4 << 20)  # 16 MB
    dispatch_ms = _dispatch_rtt_ms(20)
    launch_samples = _launch_overhead_samples(4, 20)
    launch_ms = float(np.median(launch_samples))

    # Sparse dirty-row path (ref: test_matrix_perf.cpp sparse variants):
    # dirty rows per round, dirty-only whole-table get — measured on
    # the DEVICE path (host bitmap bookkeeping, HBM payload: deltas
    # push as device arrays, dirty values reply as device arrays). The
    # reference-shaped host-buffer variant is timed alongside; on a
    # tunneled device it is bounded by host<->device bandwidth, which
    # the tunnel numbers below make interpretable.
    # (In-process tables skip the sparse wire filter automatically —
    # there is no wire.)
    sparse = mv.create_matrix_table(num_row, num_col, is_sparse=True)
    sparse.get_dirty_device()  # initial full sync marks everything clean
    dirty_n = num_row // 10  # the reference perf test's p/10 fraction
    rows = np.arange(dirty_n, dtype=np.int32) * 10
    dev_delta = jnp.ones((dirty_n, num_col), jnp.float32)
    jax.block_until_ready(dev_delta)
    opt = AddOption(worker_id=1)  # dirties the rows for worker 0
    # One untimed roundtrip compiles the dirty gather/scatter bucket.
    sparse.add_rows(rows, dev_delta, option=opt)
    _, warm_vals = sparse.get_dirty_device()
    float(warm_vals[0, 0])
    start = time.perf_counter()
    sparse_iters = 10
    vals = None
    for _ in range(sparse_iters):
        sparse.add_rows(rows, dev_delta, option=opt)
        _, vals = sparse.get_dirty_device()  # only the dirty rows
    float(vals[0, 0])  # force the dispatched chain
    sparse_elapsed = time.perf_counter() - start
    sparse_bytes = dirty_n * num_col * 4 * 2  # add + dirty-row get
    sparse_gbps = sparse_bytes * sparse_iters / sparse_elapsed / 1e9

    # FUSED roundtrip (r5): the -4 extension composes the add and the
    # dirty get into ONE compiled program server-side — one launch per
    # iteration instead of two — and the caller keeps a device mirror
    # of its row ids (the per-call id upload otherwise rides the
    # ~35 MB/s tunnel).
    from multiverso_tpu.updater.engine import pad_ids
    dev_rows = jnp.asarray(pad_ids(rows, num_row))  # bucket-padded mirror
    _, f_vals = sparse.add_get_dirty_device(rows, dev_delta,
                                            option=opt, get_worker=0,
                                            row_ids_device=dev_rows)
    float(f_vals[0, 0])  # warm the fused compile
    start = time.perf_counter()
    for _ in range(sparse_iters):
        _, f_vals = sparse.add_get_dirty_device(rows, dev_delta,
                                                option=opt,
                                                get_worker=0,
                                                row_ids_device=dev_rows)
    float(f_vals[0, 0])
    fused_gbps = sparse_bytes * sparse_iters \
        / (time.perf_counter() - start) / 1e9

    # Launch overhead with a BIG donated buffer argument — the sparse
    # roundtrip's actual program shape (the tiny-arg launch_ms above
    # understates it: big-argument launches cost 3-10x more on the
    # tunneled platform, weather-dependent).
    big = jnp.zeros((num_row, 128), jnp.float32)
    bump = jax.jit(lambda t: t.at[0, 0].add(1.0), donate_argnums=0)
    big = bump(big)
    float(big[0, 0])
    t0 = time.perf_counter()
    for _ in range(10):
        big = bump(big)
    float(big[0, 0])
    launch_big_ms = (time.perf_counter() - t0) / 10 * 1e3
    del big
    # Platform bound for the roundtrip (VERDICT r4 weak #3): each
    # unfused iteration is 2 dependent big-argument program launches,
    # so the launch floor caps it at payload/(2*launch_big_ms)
    # regardless of code; the fused form's cap is one launch. Record
    # caps and achieved fractions so the 1.6 GB/s bar is auditable
    # against the measured weather, not prose.
    sparse_implied_cap = sparse_bytes / (2 * launch_big_ms / 1e3) / 1e9
    fused_implied_cap = sparse_bytes / (launch_big_ms / 1e3) / 1e9

    # Host-buffer variant (the reference API shape: Get fills caller
    # memory) for comparison.
    buf = np.zeros((num_row, num_col), np.float32)
    row_delta = np.ones((dirty_n, num_col), np.float32)
    sparse.get(out=buf)
    start = time.perf_counter()
    for _ in range(2):
        sparse.add_rows(rows, row_delta, option=opt)
        sparse.get(out=buf)
    host_sparse_gbps = sparse_bytes * 2 / (time.perf_counter() - start) \
        / 1e9
    mv.shutdown()

    # Scatter/sweep microbench (VERDICT r3 #2): slope-timed — T(G_hi) -
    # T(G_lo) of an in-jit scan cancels the ~100ms readback RTT that
    # made single-op timings claim scatter was O(table).
    def slope(make, lo=4, hi=12):
        def run_g(g):
            fn = make(g)
            t_val = jnp.zeros((num_row, 128), jnp.float32)
            out = fn(t_val)
            float(jnp.ravel(out)[0])
            best = float("inf")
            for _ in range(3):
                t_val = jnp.zeros((num_row, 128), jnp.float32)
                float(t_val[0, 0])
                t0 = time.perf_counter()
                out = fn(t_val)
                float(jnp.ravel(out)[0])
                best = min(best, time.perf_counter() - t0)
            return best
        return (run_g(hi) - run_g(lo)) / (hi - lo)

    import functools as _ft
    k = 32768
    ids_scan = jax.random.randint(jax.random.PRNGKey(0), (12, k), 0,
                                  num_row, jnp.int32)
    delta_rows = jnp.ones((k, 128), jnp.float32)

    def make_scatter(g):
        @_ft.partial(jax.jit, donate_argnums=0, static_argnums=1)
        def f(t, g):
            def body(t, i):
                return t.at[i].add(delta_rows), 0.0
            t, _ = jax.lax.scan(body, t, ids_scan[:g])
            return t
        return lambda t: f(t, g)

    # Gather slope needs a BIGGER row set than scatter: a 32K-row
    # gather (~16 MB) finishes in ~0.2 ms, far under the min-of-3
    # timing noise, and the r5.0 run measured a null slope. 256K rows
    # per step puts the per-step cost well above the noise floor.
    k_gather = 262144
    ids_gather = jax.random.randint(jax.random.PRNGKey(1),
                                    (12, k_gather), 0, num_row,
                                    jnp.int32)

    def make_gather(g):
        @_ft.partial(jax.jit, static_argnums=1)
        def f(t, g):
            def body(acc, i):
                # Reduce the gathered rows into the carry scalar: the
                # output depends on every gather, so none can be
                # dead-code-eliminated.
                return acc + t[i].sum(), None
            acc, _ = jax.lax.scan(body, jnp.float32(0), ids_gather[:g])
            return acc
        return lambda t: f(t, g)

    def make_sweep(g):
        @_ft.partial(jax.jit, donate_argnums=0, static_argnums=1)
        def f(t, g):
            def body(t, _):
                return t + 1.0, 0.0
            t, _ = jax.lax.scan(body, t, jnp.arange(g))
            return t
        return lambda t: f(t, g)

    def gbps(io_bytes, slope_s):
        # A non-positive slope means the measurement noise exceeded the
        # per-step cost (tunnel weather) — report None, not infinity.
        if slope_s <= 1e-5:
            return None
        return round(io_bytes / slope_s / 1e9, 2)

    scatter_gbps = gbps(2 * k * 128 * 4, slope(make_scatter))
    gather_gbps = gbps(k_gather * 128 * 4, slope(make_gather))
    sweep_gbps = gbps(2 * num_row * 128 * 4, slope(make_sweep))

    return {"add_gbps": round(add_gbps, 3),
            "get_gbps": round(get_gbps, 3),
            "scatter_32k_rows_gbps": scatter_gbps,
            "gather_256k_rows_gbps": gather_gbps,
            "table_sweep_gbps": sweep_gbps,
            "sparse_dirty_roundtrip_gbps": round(sparse_gbps, 3),
            "sparse_dirty_fused_gbps": round(fused_gbps, 3),
            "sparse_dirty_launch_cap_gbps": round(sparse_implied_cap, 3),
            "sparse_dirty_fraction_of_cap": round(
                sparse_gbps / sparse_implied_cap, 3),
            "sparse_fused_launch_cap_gbps": round(fused_implied_cap, 3),
            "sparse_fused_fraction_of_cap": round(
                fused_gbps / fused_implied_cap, 3),
            "program_launch_big_arg_ms": round(launch_big_ms, 3),
            "sparse_dirty_hostbuf_gbps": round(host_sparse_gbps, 3),
            "tunnel_upload_mbps": round(up_mbps, 1),
            "tunnel_download_mbps": round(down_mbps, 1),
            "dispatch_roundtrip_ms": round(dispatch_ms, 3),
            "program_launch_ms": round(launch_ms, 3),
            "program_launch_ms_samples": [round(x, 3)
                                          for x in launch_samples]}


def _phase(name: str, fn, *args, **kw):
    """Run one bench phase with stderr progress + timing (stdout carries
    only cumulative JSON result lines — the last one wins)."""
    print(f"[bench] {name}...", file=sys.stderr, flush=True)
    start = time.perf_counter()
    out = fn(*args, **kw)
    dt = time.perf_counter() - start
    _phase.seconds[name] = round(dt, 1)
    print(f"[bench] {name} done in {dt:.1f}s", file=sys.stderr, flush=True)
    return out


_phase.seconds = {}


# ---------------------------------------------------------------------------
# Loss-proof harness (VERDICT r4 #1): round 4's entire perf story died in a
# driver timeout because the bench printed its single JSON line only at the
# very end. Three defenses, in depth:
#   1. EMIT AFTER EVERY PHASE — the cumulative result is reprinted to stdout
#      as a complete JSON line after each phase lands; whatever kills the
#      process, everything already finished is already on stdout (the
#      driver parses the last complete JSON line).
#   2. SIGTERM/SIGINT handler — `timeout` sends SIGTERM first; the handler
#      prints one final cumulative line and exits, so even the in-flight
#      phase's partial absence is recorded explicitly.
#   3. GLOBAL WALL BUDGET — before each phase, elapsed + a conservative
#      worst-case estimate is checked against the budget; phases that no
#      longer fit are skipped with a note instead of being started.
# Deterministic CPU baselines (cpp_baseline, cpu_baseline) are additionally
# cached on disk keyed by a config+source hash (~12 min recovered per run).

WALL_BUDGET_SEC = float(os.environ.get("BENCH_WALL_BUDGET", "1500"))
_BENCH_T0 = time.monotonic()

# Conservative worst-case phase costs (sec) on this platform, from the r3/r4
# driver tails — used only for the skip decision, never for timing.
_PHASE_EST = {
    "write_corpus": 8, "build_dictionary": 25, "weather_probe": 30,
    "cpp_baseline": 340, "cpu_baseline": 430,
    "local_train": 100, "ps_train": 110,
    "quality_local": 190, "quality_ps": 180,
    "ps_hostbatch": 70, "hs_train": 60,
    "ps_two_workers": 60, "ps_two_servers": 150,
    "tcp_one_process": 65, "tcp_two_process": 110,
    "matrix_bandwidth": 60, "local_retime": 60,
    "wire_codec": 15, "zero_copy": 45, "client_cache": 45,
    "server_fusion": 60,
    "allreduce": 260,
    "observability": 60, "elastic": 110, "autotune": 120,
    "many_connections": 90,
}


class _Result:
    """Cumulative bench result: phases merge fields in as they finish,
    ``emit()`` prints the whole thing as one JSON line each time."""

    def __init__(self):
        self.doc = {
            "metric": "wordembedding_words_per_sec_per_chip",
            "value": None, "unit": "words/s", "vs_baseline": None,
            "detail": {"phase_seconds": _phase.seconds,
                       "wall_budget": {"budget_sec": WALL_BUDGET_SEC,
                                       "skipped": [],
                                       "interrupted": None}},
        }

    def merge(self, **fields) -> None:
        self.doc["detail"].update(fields)
        # Every merge lands on stdout immediately — "merged but not yet
        # emitted" is exactly the window a kill would erase.
        self.emit()

    _last_json = "{}"

    def emit(self) -> None:
        self.doc["detail"]["wall_budget"]["elapsed_sec"] = round(
            time.monotonic() - _BENCH_T0, 1)
        # ONE write call per line: the SIGTERM handler may fire mid-emit
        # and append its own line — a torn multi-part write would leave
        # no complete final JSON line for the driver to parse.
        self._last_json = json.dumps(self.doc)
        sys.stdout.write(self._last_json + "\n")
        sys.stdout.flush()

    def run(self, name: str, fn, *args, **kw):
        """Budget-checked phase: skip (recording why) if the worst-case
        estimate no longer fits; emit the cumulative line after every
        completion OR failure."""
        elapsed = time.monotonic() - _BENCH_T0
        est = kw.pop("est", None) or _PHASE_EST.get(name, 60)
        if elapsed + est > WALL_BUDGET_SEC:
            print(f"[bench] SKIP {name}: {elapsed:.0f}s elapsed + "
                  f"~{est}s estimate exceeds {WALL_BUDGET_SEC:.0f}s "
                  "budget", file=sys.stderr, flush=True)
            self.doc["detail"]["wall_budget"]["skipped"].append(name)
            self.emit()  # the skip record must not wait for a later
            # phase to land on stdout
            return None
        try:
            return _phase(name, fn, *args, **kw)
        except Exception as exc:  # noqa: BLE001 - a phase failure must
            # not take down the phases that already landed or follow
            print(f"[bench] {name} FAILED: {exc!r}", file=sys.stderr,
                  flush=True)
            self.merge(**{name + "_error": str(exc)[:300]})
            return None
        finally:
            self.emit()


def _install_kill_emitter(result: _Result) -> None:
    import signal

    def _on_kill(signum, frame):  # noqa: ARG001
        # The main thread may be mid-merge (dict resizing) — a fresh
        # json.dumps can raise mid-iteration. Fall back to re-printing
        # the last complete serialized line: losing the "interrupted"
        # marker is acceptable; losing the whole record is not.
        try:
            result.doc["detail"]["wall_budget"]["interrupted"] = \
                signal.Signals(signum).name
            result.emit()
        except Exception:  # noqa: BLE001
            sys.stdout.write(result._last_json + "\n")
        sys.stdout.flush()
        os._exit(98)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_kill)


def _baseline_cache_path(name: str, src_paths) -> str:
    """Cache file path for a deterministic baseline. Key = hash of the
    bench config constants + the baseline's source files + the
    bench-side logic they depend on; any edit invalidates."""
    import hashlib
    import inspect
    h = hashlib.sha256()
    h.update(repr((VOCAB, SENTENCES, WORDS_PER_SENTENCE, EPOCHS, BATCH,
                   DIM, NEG, MIN_COUNT, NEG_BLOCK, LOCAL_CENTERS,
                   LOCAL_DISPATCH)).encode())
    # The baselines also depend on bench-side logic that is not in the
    # constants: the corpus generator and the baseline runners (CLI
    # args, compile flags, the cpu twin's run_local). Hash their SOURCE
    # so editing any of them invalidates the cache.
    for bench_fn in (write_corpus, _build, run_local, cpu_baseline,
                     cpp_baseline):
        h.update(inspect.getsource(bench_fn).encode())
    for p in sorted(src_paths):
        with open(p, "rb") as f:
            h.update(f.read())
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(here, ".bench_cache",
                        f"{name}-{h.hexdigest()[:16]}.json")


def _cached_baseline(name: str, src_paths, fn, *args):
    """Disk cache for the two DETERMINISTIC baselines: same corpus
    constants + same sources => same numbers, so recomputing ~12 min of
    CPU work every bench run is pure waste (VERDICT r4 weak #6). The
    loss/separation fields are exactly reproducible; the cached TIMING
    fields carry whatever load the populating run saw, which is why the
    reply is marked ``cached`` (populate from an uncontended run)."""
    path = _baseline_cache_path(name, src_paths)
    if os.path.exists(path):
        with open(path) as f:
            out = json.load(f)
        out["cached"] = True
        print(f"[bench] {name}: cache hit ({os.path.basename(path)})",
              file=sys.stderr, flush=True)
        return out
    out = fn(*args)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp_path = path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(out, f)
    os.replace(tmp_path, path)
    return out


def _baseline_est(name: str, src_paths) -> int:
    """Skip-check estimate for a cached baseline: seconds when the
    cache file exists, the worst-case recompute estimate otherwise."""
    if os.path.exists(_baseline_cache_path(name, src_paths)):
        return 10
    return _PHASE_EST[name]


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache in the repo (gitignored): the
    big word2vec programs take 60-200s to compile on this platform, and
    the cache survives across bench runs on the same machine."""
    try:
        import jax
        cache_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception as exc:  # noqa: BLE001 - cache is best-effort
        print(f"[bench] compilation cache unavailable: {exc}",
              file=sys.stderr)


def main() -> None:
    # Handler FIRST: the compilation-cache setup imports jax (slow cold)
    # and a TERM landing before installation would die silently.
    result = _Result()
    _install_kill_emitter(result)
    _enable_compilation_cache()
    here = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp()
    corpus = os.path.join(tmp, "corpus.txt")
    result.merge(setup={
        "vocab_raw": VOCAB, "min_count": MIN_COUNT,
        "sentences": SENTENCES, "epochs": EPOCHS, "batch": BATCH,
        "dim": DIM, "negative": NEG, "neg_block": NEG_BLOCK,
        "quality_mode": {"per_pair": True, "centers": QUALITY_C,
                         "epochs": QUALITY_EPOCHS},
        "ps_batches": PS_MAX_BATCHES,
        "corpus": "synthetic 2-topic banded Zipf "
                  "(no egress: enwik9 unavailable)"})
    result.emit()  # a complete (if empty) line exists from second zero
    weather = result.run("weather_probe", weather_probe)
    if weather:
        result.merge(weather_at_start=weather)
    codec = result.run("wire_codec", run_wire_codec)
    if codec:
        result.merge(wire_codec=codec)
    zero_copy = result.run("zero_copy", run_zero_copy)
    if zero_copy:
        result.merge(zero_copy=zero_copy)
    allreduce = result.run("allreduce", run_allreduce)
    if allreduce:
        result.merge(allreduce=allreduce)
    _phase("write_corpus", write_corpus, corpus)
    prebuilt = _phase("build_dictionary", _build, corpus)
    result.doc["detail"]["setup"]["vocab_actual"] = prebuilt[0].size

    # Phases run in IMPORTANCE order: if the wall budget truncates the
    # run, what remains on stdout is the most valuable prefix. The two
    # deterministic CPU baselines are disk-cached (first run pays, every
    # later run is free), so cpp lands first cheaply and cpu can wait.
    cpp_srcs = [os.path.join(here, "native", "baseline",
                             "word2vec_baseline.cpp")]
    cpp = result.run("cpp_baseline", _cached_baseline, "cpp_baseline",
                     cpp_srcs, cpp_baseline, corpus, tmp, prebuilt[0],
                     est=_baseline_est("cpp_baseline", cpp_srcs)) \
        or {"error": "skipped or failed"}
    cpp_sep = cpp.get("topic_separation", CPP_SEP_FALLBACK)
    cpp_wps = cpp.get("words_per_sec")
    result.merge(cpp_baseline=cpp)

    local = result.run("local_train", run_local, corpus, prebuilt)
    if local:
        result.doc["value"] = round(local["wps"], 0)
        if cpp_wps:
            # The number to beat: the C++/OpenMP word2vec on this
            # host's CPU (BASELINE.md north star: >=10x CPU words/sec).
            result.doc["vs_baseline"] = round(local["wps"] / cpp_wps, 3)
        result.merge(
            local_median_batch_words_per_sec=local["median_batch_wps"],
            # Pure host arithmetic — never gated on the device fetch.
            utilization=utilization(local["pairs_per_sec"],
                                    local["centers_per_sec"]))
        result.doc["detail"]["mfu"] = \
            result.doc["detail"]["utilization"]["mfu"]
        try:
            # Live device work (row gather + readback over the tunnel)
            # — a transient failure here must not kill the later phases.
            result.merge(
                # Row-fetch form: np.asarray(model.embeddings) would
                # pull the whole table over the host link for 48 rows.
                local_topic_separation=round(float(topic_separation(
                    None, local["dictionary"],
                    fetch_rows=lambda ids: np.asarray(
                        local["model"]._emb_in[ids]))), 4))
        except Exception as exc:  # noqa: BLE001
            result.merge(local_topic_separation_error=str(exc)[:200])
        result.emit()

    ps = result.run("ps_train", run_ps, corpus, prebuilt)
    if ps:
        result.merge(
            ps_words_per_sec=round(ps["wps"], 0),
            ps_grouped_words_per_sec=ps.get("grouped_wps"),
            ps_blocks_per_dispatch=PS_GROUP,
            ps_cold_words_per_sec=ps["cold_wps"],
            ps_warmup_seconds=ps["warmup_seconds"],
            ps_median_batch_words_per_sec=ps["median_batch_wps"],
            ps_avg_loss=ps["avg_loss"],
            ps_topic_separation=ps["separation"],
            ps_dashboard=ps["dashboard"],
            ps_xprof_trace_dir=ps["xprof_trace_dir"])
        if local:
            result.merge(ps_vs_local=round(ps["wps"] / local["wps"], 3))
        result.emit()

    quality_local = result.run("quality_local", run_quality, prebuilt,
                               cpp_sep, False) or {}
    # Merge EACH quality result as it lands (not after both): a kill
    # during the second phase must not erase the first's record.
    result.merge(quality_local=quality_local)
    quality_ps = result.run("quality_ps", run_quality, prebuilt,
                            cpp_sep, True) or {}
    result.merge(
        quality_ps=quality_ps,
        time_to_cpp_quality_sec={
            "local": quality_local.get("time_to_cpp_quality_sec"),
            "ps": quality_ps.get("time_to_cpp_quality_sec"),
            "cpp_elapsed_sec": cpp.get("elapsed_sec")})

    # Cross-process PS over TCP: the 2-process number is the record that
    # must beat the C++ baseline (VERDICT r4 #3), so it runs BEFORE the
    # 1-process continuity point.
    tcp2 = result.run("tcp_two_process", run_tcp_processes, corpus,
                      prebuilt, 2, tmp)
    tcp = {"two_process": tcp2,
           # None (not False) when either operand is missing: a skipped
           # phase must not read as "lost to the baseline".
           "beats_cpp_baseline": bool(
               tcp2["aggregate_wps"] > cpp_wps)
           if (tcp2 and cpp_wps) else None,
           "note": "CPU backend; this host has ONE core, so two "
                   "processes time-share it"}
    result.merge(tcp_cross_process=tcp)

    two_servers = result.run("ps_two_servers", run_ps_two_servers,
                             prebuilt, tmp)
    if two_servers:
        result.merge(ps_two_servers=two_servers,
                     ps_two_servers_vs_single=two_servers.get(
                         "vs_single_same_window"))

    elastic = result.run("elastic", run_elastic, tmp)
    if elastic:
        result.merge(elastic=elastic)

    cache = result.run("client_cache", run_client_cache)
    if cache:
        result.merge(client_cache=cache)

    fusion = result.run("server_fusion", run_server_fusion)
    if fusion:
        result.merge(server_fusion=fusion)

    obs = result.run("observability", run_observability)
    if obs:
        result.merge(observability=obs)

    serving = result.run("serving", run_serving)
    if serving:
        result.merge(serving=serving)

    autotune = result.run("autotune", run_autotune)
    if autotune:
        result.merge(autotune=autotune)

    fleet = result.run("serving_fleet", run_serving_fleet, tmp)
    if fleet:
        result.merge(serving_fleet=fleet)

    manyconn = result.run("many_connections", run_many_connections,
                          tmp)
    if manyconn:
        result.merge(many_connections=manyconn)

    matrix = result.run("matrix_bandwidth", matrix_bandwidth)
    if matrix:
        result.merge(matrix_table_bandwidth=matrix)
        if local:
            util = result.doc["detail"].get("utilization")
            if util is not None:
                util["step_time_decomposition"] = \
                    step_decomposition(local, matrix)
                result.emit()

    cpu_srcs = sorted(glob.glob(os.path.join(
        here, "multiverso_tpu", "models", "wordembedding", "*.py")))
    cpu = result.run("cpu_baseline", _cached_baseline, "cpu_baseline",
                     cpu_srcs, cpu_baseline, corpus,
                     est=_baseline_est("cpu_baseline", cpu_srcs))
    if cpu and local:
        # Fixed-seed full-run comparison: the CPU twin runs ALL epochs
        # with the same seeds/config, so every epoch has a rel-diff.
        rel = [round(abs(t - c) / max(abs(c), 1e-9), 4)
               for t, c in zip(local["epoch_losses"],
                               cpu["epoch_losses"])]
        result.merge(
            cpu_backend_words_per_sec=round(cpu["wps"], 0),
            loss_parity={"tpu_epoch_losses": local["epoch_losses"],
                         "cpu_epoch_losses": cpu["epoch_losses"],
                         "epoch_rel_diff": rel,
                         "epoch0_rel_diff": rel[0] if rel else None})
    result.merge(loss_curves={
        "cpp_epoch_losses": cpp.get("epoch_losses"),
        "tpu_quality_epoch_losses": quality_local.get("epoch_losses"),
        "tpu_fast_epoch_losses": local["epoch_losses"] if local
        else None})

    hostbatch = result.run("ps_hostbatch", run_hostbatch, prebuilt)
    if hostbatch:
        result.merge(ps_hostbatch_words_per_sec=hostbatch.get("wps"),
                     ps_hostbatch_batch_size=hostbatch.get("batch_size"))
    hs = result.run("hs_train", run_hs, prebuilt)
    if hs:
        result.merge(hs_train=hs)
    two_workers = result.run("ps_two_workers", run_ps_two_workers,
                             prebuilt)
    if two_workers:
        result.merge(ps_two_workers=two_workers)
    tcp1 = result.run("tcp_one_process", run_tcp_processes, corpus,
                      prebuilt, 1, tmp)
    if tcp1:
        tcp["one_process"] = tcp1
        if tcp2:
            tcp["two_vs_one"] = round(tcp2["aggregate_wps"]
                                      / max(tcp1["aggregate_wps"], 1), 3)

    # Late re-timing of the headline path (~35s, programs already
    # compiled by local_train — which is also why this only runs when
    # local_train did: warm=False would otherwise compile inside the
    # timed window, and with no first measurement there is nothing to
    # compare against). Launch weather swings 5-50x across hours, and
    # one early-vs-late pair makes intra-run drift visible — a
    # degraded `value` is then self-explaining instead of mysterious.
    # `value` itself stays the FIRST measurement, as in every round.
    if local:
        late = result.run("local_retime", run_local, corpus, prebuilt,
                          1, EPOCHS, False)
        if late:
            result.merge(local_late_median_batch_words_per_sec=late[
                "median_batch_wps"],
                local_late_vs_first=round(
                    late["median_batch_wps"]
                    / max(local["median_batch_wps"], 1), 3))
    result.emit()


if __name__ == "__main__":
    sys.exit(main())
