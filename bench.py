"""Benchmark entry point for the driver.

Primary metric = the north-star workload: WordEmbedding (skip-gram +
negative sampling) words/sec on one chip, trained end to end through the
framework's batched jitted step (model.py) with the background loader —
the TPU re-design of the reference's OpenMP word2vec
(ref: Applications/WordEmbedding/src/wordembedding.cpp,
distributed_wordembedding.cpp). ``vs_baseline`` is measured, not assumed:
the same framework code runs in a subprocess on the host CPU backend (the
stand-in for the reference's CPU-node word2vec; BASELINE.json publishes no
absolute numbers).

The reference's MatrixTable bandwidth harness
(ref: Test/test_matrix_perf.cpp) rides along in ``detail``.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

CORPUS_SENTENCES = 8000
EPOCHS = 3
BATCH = 32768


def write_corpus(path: str) -> None:
    rng = np.random.default_rng(0)
    probs = 1.0 / np.arange(1, 50001) ** 1.1
    probs /= probs.sum()
    with open(path, "w") as f:
        for _ in range(CORPUS_SENTENCES):
            ids = rng.choice(50000, size=40, p=probs)
            f.write(" ".join(f"w{i}" for i in ids) + "\n")


def run_word2vec(corpus: str) -> float:
    from multiverso_tpu.models.wordembedding import (BlockLoader,
                                                     Dictionary,
                                                     TokenizedCorpus,
                                                     Word2Vec,
                                                     Word2VecConfig,
                                                     iter_pair_batches)
    dictionary = Dictionary.build(corpus, min_count=5)
    tokenized = TokenizedCorpus.build(dictionary, corpus)
    config = Word2VecConfig(embedding_size=128, window=5, negative=5,
                            epochs=EPOCHS, batch_size=BATCH, sample=1e-3)
    model = Word2Vec(config, dictionary)
    warm = next(iter(iter_pair_batches(dictionary, tokenized,
                                       batch_size=BATCH, window=5,
                                       subsample=1e-3, seed=99)))
    model.train_batch(warm)  # compile outside the timed region
    warm_words = model.trained_words  # exclude warmup from the numerator
    start = time.perf_counter()
    losses = []
    for epoch in range(EPOCHS):
        for batch in BlockLoader(iter_pair_batches(
                dictionary, tokenized, batch_size=BATCH, window=5,
                subsample=1e-3, seed=epoch)):
            losses.append(model.train_batch_async(batch))
    final_loss = float(losses[-1])  # forces completion of the whole chain
    elapsed = time.perf_counter() - start
    assert np.isfinite(final_loss)
    return (model.trained_words - warm_words) / elapsed


def cpu_baseline(corpus: str) -> float:
    """Same algorithm, host CPU backend, separate process."""
    code = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import bench\n"
        f"print('WPS', bench.run_word2vec({corpus!r}))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=os.path.dirname(
        os.path.abspath(__file__)), env=env, capture_output=True,
        text=True, timeout=900)
    for line in out.stdout.splitlines():
        if line.startswith("WPS "):
            return float(line.split()[1])
    raise RuntimeError(f"cpu baseline failed: {out.stderr[-500:]}")


def matrix_bandwidth() -> dict:
    import jax.numpy as jnp

    import multiverso_tpu as mv

    num_row, num_col, iters = 1_000_000, 50, 10
    nbytes = num_row * num_col * 4
    mv.init([])
    table = mv.create_matrix_table(num_row, num_col)
    delta = jnp.ones((num_row, num_col), jnp.float32)
    _ = float(delta[0, 0])
    table.add(delta)
    out = table.get_device()
    _ = float(out[0, 0])
    start = time.perf_counter()
    ids = [table.add_async(delta) for _ in range(iters)]
    for msg_id in ids:
        table.wait(msg_id)
    out = table.get_device()
    _ = float(out[0, 0])
    add_gbps = nbytes / ((time.perf_counter() - start) / (iters + 1)) / 1e9
    start = time.perf_counter()
    for _ in range(iters):
        out = table.get_device()
    _ = float(out[0, 0])
    get_gbps = nbytes / ((time.perf_counter() - start) / iters) / 1e9
    mv.shutdown()
    return {"add_gbps": round(add_gbps, 3), "get_gbps": round(get_gbps, 3)}


def main() -> None:
    tmp = tempfile.mkdtemp()
    corpus = os.path.join(tmp, "corpus.txt")
    write_corpus(corpus)
    tpu_wps = run_word2vec(corpus)
    try:
        cpu_wps = cpu_baseline(corpus)
    except Exception as exc:  # noqa: BLE001 - report without a baseline
        cpu_wps = None
        baseline_err = str(exc)[:200]
    matrix = matrix_bandwidth()
    result = {
        "metric": "wordembedding_words_per_sec_per_chip",
        "value": round(tpu_wps, 0),
        "unit": "words/s",
        "vs_baseline": round(tpu_wps / cpu_wps, 3) if cpu_wps else None,
        "detail": {
            "cpu_backend_words_per_sec": round(cpu_wps, 0) if cpu_wps
            else baseline_err,
            "matrix_table_bandwidth": matrix,
            "setup": {"sentences": CORPUS_SENTENCES, "epochs": EPOCHS,
                      "batch": BATCH, "dim": 128, "negative": 5},
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
