// libmultiverso.so — C ABI shim over the TPU-native Python runtime.
//
// Byte-compatible with the reference's c_api
// (ref: include/multiverso/c_api.h:14-54, src/c_api.cpp:10-93): the same
// exported symbols, float-only Array/Matrix tables, and the opaque
// TableHandler lifecycle, so the reference's ctypes/LuaJIT-FFI/C# bindings
// load this library unmodified. Instead of an MPI actor system behind the
// ABI, each call forwards into the embedded (or host) CPython interpreter
// running multiverso_tpu; tensors cross the boundary as zero-copy
// memoryviews (multiverso_tpu/capi.py wraps them as numpy arrays).
//
// Works in two hosting modes:
//  - loaded into an existing Python process (ctypes): attaches to the
//    running interpreter via PyGILState;
//  - loaded by a non-Python host (Lua/C#/C++): initializes an embedded
//    interpreter on MV_Init.

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

namespace {

bool g_owns_interpreter = false;

// Bring up the embedded interpreter when a non-Python host calls any
// entry point before MV_Init (MV_NetBind/MV_NetConnect legitimately run
// first); acquiring the GIL on an uninitialized runtime is fatal.
void ensure_interpreter() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Release the GIL so Gil{} works uniformly afterwards.
    PyEval_SaveThread();
  }
}

struct Gil {
  PyGILState_STATE state;
  Gil() : state(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state); }
};

void fatal_on_pyerr(const char* where) {
  if (PyErr_Occurred()) {
    std::fprintf(stderr, "[multiverso c_api] python error in %s:\n", where);
    PyErr_Print();
    std::abort();
  }
}

PyObject* capi_module() {
  static PyObject* module = nullptr;
  if (module == nullptr) {
    module = PyImport_ImportModule("multiverso_tpu.capi");
    fatal_on_pyerr("import multiverso_tpu.capi");
  }
  return module;
}

// Call multiverso_tpu.capi.<name>(*args); returns new reference.
PyObject* call(const char* name, PyObject* args) {
  PyObject* fn = PyObject_GetAttrString(capi_module(), name);
  fatal_on_pyerr(name);
  PyObject* result = PyObject_CallObject(fn, args);
  fatal_on_pyerr(name);
  Py_XDECREF(fn);
  Py_XDECREF(args);
  return result;
}

PyObject* float_view(float* data, int size, int writable) {
  return PyMemoryView_FromMemory(reinterpret_cast<char*>(data),
                                 static_cast<Py_ssize_t>(size) * 4,
                                 writable ? PyBUF_WRITE : PyBUF_READ);
}

PyObject* int_view(int* data, int size) {
  return PyMemoryView_FromMemory(reinterpret_cast<char*>(data),
                                 static_cast<Py_ssize_t>(size) * 4,
                                 PyBUF_READ);
}

}  // namespace

extern "C" {

typedef void* TableHandler;

void MV_Init(int* argc, char* argv[]) {
  if (!Py_IsInitialized()) {
    g_owns_interpreter = true;
  }
  ensure_interpreter();
  Gil gil;
  PyObject* args_list = PyList_New(0);
  int n = (argc != nullptr) ? *argc : 0;
  for (int i = 0; i < n; ++i) {
    PyObject* s = PyUnicode_FromString(argv[i] ? argv[i] : "");
    PyList_Append(args_list, s);
    Py_DECREF(s);
  }
  Py_XDECREF(call("init", Py_BuildValue("(N)", args_list)));
}

void MV_ShutDown() {
  {
    Gil gil;
    Py_XDECREF(call("shutdown", nullptr));
  }
  // The embedded interpreter (non-Python hosts) stays alive: JAX runtimes
  // do not survive re-initialization, and the reference keeps MPI alive
  // across MV_ShutDown(false) the same way.
}

void MV_Barrier() {
  Gil gil;
  Py_XDECREF(call("barrier", nullptr));
}

// App-driven deployment without a machine file — the reference's C++ API
// pair (ref: include/multiverso/multiverso.h:55-64, zmq_net.h:63-109):
// MV_NetBind declares this process's rank + endpoint, MV_NetConnect
// supplies every rank's endpoint; a following MV_Init then bootstraps
// the TCP mesh from this instead of -machine_file.
void MV_NetBind(int rank, char* endpoint) {
  ensure_interpreter();
  Gil gil;
  Py_XDECREF(call("net_bind",
                  Py_BuildValue("(is)", rank, endpoint ? endpoint : "")));
}

void MV_NetConnect(int* ranks, char* endpoints[], int size) {
  ensure_interpreter();
  Gil gil;
  PyObject* rank_list = PyList_New(0);
  PyObject* endpoint_list = PyList_New(0);
  for (int i = 0; i < size; ++i) {
    PyObject* r = PyLong_FromLong(ranks ? ranks[i] : i);
    PyList_Append(rank_list, r);
    Py_DECREF(r);
    PyObject* e = PyUnicode_FromString(
        (endpoints && endpoints[i]) ? endpoints[i] : "");
    PyList_Append(endpoint_list, e);
    Py_DECREF(e);
  }
  Py_XDECREF(call("net_connect",
                  Py_BuildValue("(NN)", rank_list, endpoint_list)));
}

int MV_NumWorkers() {
  Gil gil;
  PyObject* result = call("num_workers", nullptr);
  long value = PyLong_AsLong(result);
  Py_XDECREF(result);
  return static_cast<int>(value);
}

int MV_WorkerId() {
  Gil gil;
  PyObject* result = call("worker_id", nullptr);
  long value = PyLong_AsLong(result);
  Py_XDECREF(result);
  return static_cast<int>(value);
}

int MV_ServerId() {
  Gil gil;
  PyObject* result = call("server_id", nullptr);
  long value = PyLong_AsLong(result);
  Py_XDECREF(result);
  return static_cast<int>(value);
}

// -- Array table (float only, as in the reference) --

void MV_NewArrayTable(int size, TableHandler* out) {
  Gil gil;
  *out = call("new_array_table", Py_BuildValue("(i)", size));
}

void MV_GetArrayTable(TableHandler handler, float* data, int size) {
  Gil gil;
  Py_XDECREF(call("get_array_table",
                  Py_BuildValue("(ON)", static_cast<PyObject*>(handler),
                                float_view(data, size, 1))));
}

void MV_AddArrayTable(TableHandler handler, float* data, int size) {
  Gil gil;
  Py_XDECREF(call("add_array_table",
                  Py_BuildValue("(ONi)", static_cast<PyObject*>(handler),
                                float_view(data, size, 0), 1)));
}

void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size) {
  Gil gil;
  Py_XDECREF(call("add_array_table",
                  Py_BuildValue("(ONi)", static_cast<PyObject*>(handler),
                                float_view(data, size, 0), 0)));
}

// -- Matrix table --

void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out) {
  Gil gil;
  *out = call("new_matrix_table", Py_BuildValue("(ii)", num_row, num_col));
}

void MV_GetMatrixTableAll(TableHandler handler, float* data, int size) {
  Gil gil;
  Py_XDECREF(call("get_matrix_all",
                  Py_BuildValue("(ON)", static_cast<PyObject*>(handler),
                                float_view(data, size, 1))));
}

void MV_AddMatrixTableAll(TableHandler handler, float* data, int size) {
  Gil gil;
  Py_XDECREF(call("add_matrix_all",
                  Py_BuildValue("(ONi)", static_cast<PyObject*>(handler),
                                float_view(data, size, 0), 1)));
}

void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data, int size) {
  Gil gil;
  Py_XDECREF(call("add_matrix_all",
                  Py_BuildValue("(ONi)", static_cast<PyObject*>(handler),
                                float_view(data, size, 0), 0)));
}

void MV_GetMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n) {
  Gil gil;
  Py_XDECREF(call("get_matrix_rows",
                  Py_BuildValue("(ONN)", static_cast<PyObject*>(handler),
                                float_view(data, size, 1),
                                int_view(row_ids, row_ids_n))));
}

void MV_AddMatrixTableByRows(TableHandler handler, float* data, int size,
                             int row_ids[], int row_ids_n) {
  Gil gil;
  Py_XDECREF(call("add_matrix_rows",
                  Py_BuildValue("(ONNi)", static_cast<PyObject*>(handler),
                                float_view(data, size, 0),
                                int_view(row_ids, row_ids_n), 1)));
}

void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data,
                                  int size, int row_ids[], int row_ids_n) {
  Gil gil;
  Py_XDECREF(call("add_matrix_rows",
                  Py_BuildValue("(ONNi)", static_cast<PyObject*>(handler),
                                float_view(data, size, 0),
                                int_view(row_ids, row_ids_n), 0)));
}

}  // extern "C"
