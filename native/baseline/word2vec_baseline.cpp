// Honest CPU baseline for the bench: a from-scratch C++ skip-gram
// word2vec trainer (negative sampling) in the style of the classic
// word2vec.c / the reference's WordEmbedding compute core
// (ref: Applications/WordEmbedding/src/wordembedding.cpp:95-125 — the
// per-window scalar FeedForward/BPOutputLayer loops; written fresh from
// the published algorithm, no code taken from either).
//
// OpenMP hogwild over sentence chunks, sigmoid lookup table, per-center
// shrunk window, unigram^0.75 negatives via Vose alias tables, linear
// lr decay in raw words — the same training semantics the TPU path
// implements, so words/sec and embedding quality are comparable.
//
// Usage:
//   word2vec_baseline <corpus> <out_vectors|-> <epochs> <dim> <window>
//                     <negative> <sample> <lr> <min_count>
// Prints one JSON line: {"words_per_sec":..., "epochs":..., ...}

#include <omp.h>

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <random>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr float kMaxExp = 6.0f;
constexpr int kExpTableSize = 1024;

struct Vocab {
  std::vector<std::string> words;
  std::vector<int64_t> counts;
  std::unordered_map<std::string, int32_t> index;
  int64_t total = 0;
};

struct Alias {
  std::vector<float> prob;
  std::vector<int32_t> alias;
};

Alias build_alias(const std::vector<double>& weights) {
  const size_t n = weights.size();
  double sum = 0;
  for (double w : weights) sum += w;
  std::vector<double> scaled(n);
  for (size_t i = 0; i < n; ++i) scaled[i] = weights[i] * n / sum;
  Alias out;
  out.prob.assign(n, 1.0f);
  out.alias.resize(n);
  for (size_t i = 0; i < n; ++i) out.alias[i] = static_cast<int32_t>(i);
  std::vector<int32_t> small, large;
  for (size_t i = n; i-- > 0;)
    (scaled[i] < 1.0 ? small : large).push_back(static_cast<int32_t>(i));
  while (!small.empty() && !large.empty()) {
    int32_t s = small.back(), g = large.back();
    small.pop_back();
    large.pop_back();
    out.prob[s] = static_cast<float>(scaled[s]);
    out.alias[s] = g;
    scaled[g] += scaled[s] - 1.0;
    (scaled[g] < 1.0 ? small : large).push_back(g);
  }
  return out;
}

struct XorShift {
  uint64_t state;
  explicit XorShift(uint64_t seed) : state(seed * 2654435761ULL + 1) {}
  uint64_t next() {
    state ^= state << 13;
    state ^= state >> 7;
    state ^= state << 17;
    return state;
  }
  // uniform in [0, 1)
  float uniform() { return (next() >> 40) * (1.0f / (1 << 24)); }
  int32_t below(int32_t n) { return static_cast<int32_t>(next() % n); }
};

}  // namespace

int main(int argc, char** argv) {
  if (argc < 10) {
    std::fprintf(stderr,
                 "usage: %s corpus out epochs dim window negative sample "
                 "lr min_count\n",
                 argv[0]);
    return 2;
  }
  const std::string corpus = argv[1];
  const std::string out_path = argv[2];
  const int epochs = std::atoi(argv[3]);
  const int dim = std::atoi(argv[4]);
  const int window = std::atoi(argv[5]);
  const int negative = std::atoi(argv[6]);
  const double sample = std::atof(argv[7]);
  const float init_lr = static_cast<float>(std::atof(argv[8]));
  const int64_t min_count = std::atoll(argv[9]);

  // ---- pass 1: vocabulary ----
  Vocab vocab;
  {
    std::unordered_map<std::string, int64_t> counter;
    std::ifstream in(corpus);
    if (!in) {
      std::fprintf(stderr, "cannot open %s\n", corpus.c_str());
      return 1;
    }
    std::string word;
    while (in >> word) ++counter[word];
    std::vector<std::pair<std::string, int64_t>> items(counter.begin(),
                                                       counter.end());
    // Count-descending, then lexicographic: frequent words get small ids.
    std::sort(items.begin(), items.end(), [](const auto& a, const auto& b) {
      return a.second != b.second ? a.second > b.second : a.first < b.first;
    });
    for (auto& kv : items) {
      if (kv.second < min_count) continue;
      vocab.index.emplace(kv.first, static_cast<int32_t>(vocab.words.size()));
      vocab.words.push_back(kv.first);
      vocab.counts.push_back(kv.second);
      vocab.total += kv.second;
    }
  }
  const int32_t V = static_cast<int32_t>(vocab.words.size());
  if (V == 0) return 1;

  // ---- pass 2: tokenize into sentences ----
  std::vector<int32_t> tokens;
  std::vector<int64_t> sent_offsets{0};
  {
    std::ifstream in(corpus);
    std::string line;
    while (std::getline(in, line)) {
      std::istringstream ls(line);
      std::string word;
      size_t before = tokens.size();
      while (ls >> word) {
        auto it = vocab.index.find(word);
        if (it != vocab.index.end()) tokens.push_back(it->second);
      }
      if (tokens.size() - before >= 2) sent_offsets.push_back(tokens.size());
      else tokens.resize(before);
    }
  }
  const int64_t n_tokens = static_cast<int64_t>(tokens.size());
  const size_t n_sent = sent_offsets.size() - 1;

  // ---- tables ----
  std::vector<float> keep_prob(V, 1.0f);
  if (sample > 0) {
    for (int32_t i = 0; i < V; ++i) {
      double f = static_cast<double>(vocab.counts[i]) / vocab.total;
      double r = sample / f;
      keep_prob[i] =
          static_cast<float>(std::min(std::sqrt(r) + r, 1.0));
    }
  }
  std::vector<double> neg_weights(V);
  for (int32_t i = 0; i < V; ++i)
    neg_weights[i] = std::pow(static_cast<double>(vocab.counts[i]), 0.75);
  Alias neg = build_alias(neg_weights);

  float exp_table[kExpTableSize + 1];
  for (int i = 0; i <= kExpTableSize; ++i) {
    float x = (2.0f * i / kExpTableSize - 1.0f) * kMaxExp;
    exp_table[i] = 1.0f / (1.0f + std::exp(-x));
  }
  auto sigmoid = [&](float x) -> float {
    if (x >= kMaxExp) return 1.0f;
    if (x <= -kMaxExp) return 0.0f;
    return exp_table[static_cast<int>((x / kMaxExp + 1.0f) *
                                      (kExpTableSize / 2))];
  };
  // Per-epoch average pair loss (one positive + `negative` xent terms
  // per pair, matching the TPU trainer's accounting) — the convergence
  // record the reference's apps log
  // (ref: Applications/LogisticRegression/src/logreg.cpp:41-87).
  // Table-lookup log-sigmoid keeps the cost out of the hot loop.
  std::vector<float> logsig_table(kExpTableSize);
  for (int i = 0; i < kExpTableSize; ++i)
    logsig_table[i] = std::log(std::max(exp_table[i], 1e-9f));
  auto xent = [&](float dot, float label) -> float {
    float z = label > 0.5f ? dot : -dot;  // log sigmoid(z)
    if (z >= kMaxExp) return 0.0f;
    if (z <= -kMaxExp) return -logsig_table[0];
    return -logsig_table[static_cast<int>((z / kMaxExp + 1.0f) *
                                          (kExpTableSize / 2))];
  };
  std::vector<double> epoch_losses;
  std::vector<long long> epoch_pairs;

  // ---- embeddings ----
  std::vector<float> emb_in(static_cast<size_t>(V) * dim);
  std::vector<float> emb_out(static_cast<size_t>(V) * dim, 0.0f);
  {
    XorShift rng(7);
    for (auto& x : emb_in) x = (rng.uniform() - 0.5f) / dim;
  }

  // ---- training ----
  const int64_t total_words = static_cast<int64_t>(n_tokens) * epochs;
  int64_t words_done = 0;
  auto start = std::chrono::steady_clock::now();
  for (int epoch = 0; epoch < epochs; ++epoch) {
    double loss_sum = 0.0;
    long long pair_count = 0;
#pragma omp parallel reduction(+ : loss_sum, pair_count)
    {
      std::vector<int32_t> kept;
      std::vector<float> grad_v(dim);
      XorShift rng(static_cast<uint64_t>(epoch) * 1000003 +
                   omp_get_thread_num() * 97 + 11);
#pragma omp for schedule(dynamic, 256)
      for (int64_t s = 0; s < static_cast<int64_t>(n_sent); ++s) {
        const int64_t lo = sent_offsets[s], hi = sent_offsets[s + 1];
        kept.clear();
        for (int64_t t = lo; t < hi; ++t) {
          int32_t w = tokens[t];
          if (keep_prob[w] >= 1.0f || rng.uniform() < keep_prob[w])
            kept.push_back(w);
        }
        int64_t done;
#pragma omp atomic capture
        done = words_done += hi - lo;
        float lr = init_lr *
                   std::max(1.0f - static_cast<float>(done) / total_words,
                            1e-4f);
        const int n = static_cast<int>(kept.size());
        for (int c = 0; c < n; ++c) {
          const int32_t center = kept[c];
          float* v = emb_in.data() + static_cast<size_t>(center) * dim;
          const int b = 1 + rng.below(window);  // shrunk window
          for (int o = -b; o <= b; ++o) {
            if (o == 0) continue;
            const int j = c + o;
            if (j < 0 || j >= n) continue;
            std::fill(grad_v.begin(), grad_v.end(), 0.0f);
            // one positive + `negative` sampled outputs per pair
            for (int k = 0; k <= negative; ++k) {
              int32_t target;
              float label;
              if (k == 0) {
                target = kept[j];
                label = 1.0f;
              } else {
                int32_t d = rng.below(V);
                target = rng.uniform() < neg.prob[d] ? d : neg.alias[d];
                label = 0.0f;
              }
              float* u = emb_out.data() + static_cast<size_t>(target) * dim;
              float dot = 0.0f;
              for (int i = 0; i < dim; ++i) dot += v[i] * u[i];
              const float g = (label - sigmoid(dot)) * lr;
              loss_sum += xent(dot, label);
              for (int i = 0; i < dim; ++i) grad_v[i] += g * u[i];
              for (int i = 0; i < dim; ++i) u[i] += g * v[i];
            }
            pair_count += 1;
            for (int i = 0; i < dim; ++i) v[i] += grad_v[i];
          }
        }
      }
    }
    epoch_losses.push_back(loss_sum / std::max(pair_count, 1LL));
    epoch_pairs.push_back(pair_count);
    std::fprintf(stderr, "epoch %d: avg pair loss %.4f (%lld pairs)\n",
                 epoch, epoch_losses.back(), pair_count);
  }
  auto elapsed = std::chrono::duration<double>(
                     std::chrono::steady_clock::now() - start)
                     .count();

  if (out_path != "-") {
    if (out_path.size() > 4 &&
        out_path.compare(out_path.size() - 4, 4, ".bin") == 0) {
      // Raw float32 [V, dim] plus a sibling .words file (text vectors
      // of a 1M-word vocab take minutes to parse; binary is instant).
      std::ofstream out(out_path, std::ios::binary);
      out.write(reinterpret_cast<const char*>(emb_in.data()),
                static_cast<std::streamsize>(emb_in.size() * sizeof(float)));
      std::ofstream words(out_path + ".words");
      for (int32_t w = 0; w < V; ++w) words << vocab.words[w] << "\n";
    } else {
      std::ofstream out(out_path);
      out << V << " " << dim << "\n";
      for (int32_t w = 0; w < V; ++w) {
        out << vocab.words[w];
        const float* v = emb_in.data() + static_cast<size_t>(w) * dim;
        for (int i = 0; i < dim; ++i) out << " " << v[i];
        out << "\n";
      }
    }
  }

  std::string losses_json = "[";
  for (size_t i = 0; i < epoch_losses.size(); ++i) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%s%.4f", i ? ", " : "",
                  epoch_losses[i]);
    losses_json += buf;
  }
  losses_json += "]";
  std::printf(
      "{\"words_per_sec\": %.0f, \"elapsed_sec\": %.2f, \"epochs\": %d, "
      "\"vocab\": %d, \"tokens\": %lld, \"threads\": %d, "
      "\"epoch_losses\": %s}\n",
      total_words / elapsed, elapsed, epochs, V,
      static_cast<long long>(n_tokens), omp_get_max_threads(),
      losses_json.c_str());
  return 0;
}
