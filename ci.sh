#!/usr/bin/env bash
# CI gate — the repo's equivalent of the reference's Docker test list
# (ref: deploy/docker/Dockerfile:94-113: build, unit tests, binding
# tests, mpirun -np 4 integration tests). Runnable locally and from CI.
set -euo pipefail
cd "$(dirname "$0")"

echo "== mvlint static-analysis gate =="
# Project invariants, machine-checked before anything runs: flag
# registry, wire-slot registry (cross-checked vs docs/WIRE_FORMAT.md),
# device-dispatch guarding, lock discipline, copy discipline on the
# zero-copy wire path (cross-checked vs docs/MEMORY.md),
# interprocedural thread-role blocking reachability (cross-checked vs
# docs/THREADS.md + the THREAD_ROLES registry; runtime twin is the
# -debug_locks/-role_block_budget_ms watchdog), guarded-by field/lock
# annotations, message-protocol flow (every Request reaches exactly
# one handler, every reply path counts the requester's Waiter down,
# cross-checked vs the docs/WIRE_FORMAT.md flow table both
# directions) and the wake-latch re-arm ordering (the PR-19 lost-
# wakeup shape) — twelve passes total. Fails on any non-pragma'd
# violation and prints file:line diagnostics; the trailing summary
# shows per-pass counts. (`python -m tools.mvlint --baseline ...`
# prints the same counts WITHOUT failing — drift-at-a-glance for PRs.)
# See docs/STATIC_ANALYSIS.md.
python -m tools.mvlint multiverso_tpu tests bench.py

# Stale-suppression review line, NOT a gate: pragmas that suppressed
# zero findings are listed for cleanup but never fail the build (a
# pragma can be load-bearing only on certain trees).
python -m tools.mvlint --report-unused-pragmas \
    multiverso_tpu tests bench.py | grep '^warning:' || true

echo "== mvlint self-check (seeded fixtures must still fail) =="
# The analyzers are regression-protected: a pass that silently stops
# firing would green-light real violations, so the seeded-violation
# fixtures must keep exiting with status 1 (violations found) —
# SPECIFICALLY 1: status 2 means a bad/empty path, i.e. the self-check
# itself went vacuous (fixtures moved), which must also fail loudly.
rc=0
python -m tools.mvlint tools/mvlint/fixtures > /tmp/mv_lint_fix.log 2>&1 \
    || rc=$?
if [ "$rc" -ne 1 ]; then
    cat /tmp/mv_lint_fix.log
    echo "FATAL: mvlint fixtures self-check expected exit 1, got $rc"
    exit 1
fi

echo "== mvchk model-checker gate (systematic schedules) =="
# The dynamic half of the concurrency gate (docs/STATIC_ANALYSIS.md
# "The dynamic half"): deterministic bounded-preemption exploration of
# the real MtQueue/Waiter/_VectorClock primitives on model locks, plus
# the event-loop wake protocol. The exit code is the expectation check
# both ways — every good spec must pass ALL explored schedules AND the
# known-bad pre-PR-19 wake-drain ordering must be REFUTED with a
# printed counterexample trace; a checker that blesses it has gone
# vacuous and fails here, the same self-check discipline as the mvlint
# fixtures above. Seeded-random long runs ride the slow gate.
python -m tools.mvchk
if [ "${MV_CI_SLOW:-0}" = "1" ]; then
    echo "== mvchk soak (seeded-random schedules) =="
    python -m tools.mvchk --random 300 --seed 20260807
fi

echo "== build native (c_api shim) from source =="
make -C native clean
make -C native

echo "== collection sanity (no tests silently skipped) =="
# A collection error under --continue-on-collection-errors silently
# shrinks the suite; gate on a clean collection pass so a broken import
# fails CI loudly instead of skipping its whole file.
python -m pytest tests/ --collect-only -q > /tmp/mv_collect.log 2>&1 \
    || { cat /tmp/mv_collect.log; echo "FATAL: test collection errors"; \
         exit 1; }

echo "== fast wire-codec + client-cache + allreduce subsets =="
# The wire-facing suites run first and explicitly: a regression in the
# codec frames, the versioned cache, or the collective engine must name
# itself, not hide inside the full run's output.
python -m pytest tests/test_wire_codec.py tests/test_client_cache.py -x -q

echo "== zero-copy wire path subset (golden frames / buffer pool / COW) =="
# The zero-copy transport invariants get their own named gate: frame
# byte-identity between the scatter-gather framer and the legacy flat
# serializer (header slots 0-9, codec frames, batch descriptors — the
# no-wire-break proof), buffer-pool lease safety (a blob-outlived array
# is never aliased by a recycled frame), the read-only/materialize
# copy-on-write contract, and TCP round trips with the pool active
# (tests/test_zero_copy.py; docs/MEMORY.md). The static half — mvlint
# pass 8 copy-lint, banning tobytes/bytes()/join on wire-path modules —
# already ran in the mvlint block above.
python -m pytest tests/test_zero_copy.py -x -q

echo "== shm transport subset (co-located rings / lifecycle hygiene / interop) =="
# The below-the-socket transport gets its own named gate: ring round
# trips land as read-only views INTO the shared segment, bounded
# backpressure on a saturated ring, the weakref slot-parking contract,
# oversize chunking through the receive pool, -chaos_frames coverage
# of ring sends, segment unlink on finalize/SIGKILL/rejoin (a
# /dev/shm entry or resource_tracker warning surviving a test is a
# failure), and the mixed shm+TCP 3-process byte-identity proof
# (tests/test_shm.py; docs/MEMORY.md "Below the socket"). The static
# half — copy-lint over runtime/shm.py — ran in the mvlint block.
python -m pytest tests/test_shm.py -x -q

echo "== sparse-allreduce subset (index-union reduce / switchover / sharded avg) =="
# The sparse collective tier gets its own named gate: choose_algo path
# pinning per (size, density, world), index-union merge correctness vs
# numpy, the switchover boundary (results bit-equal on both sides of
# the cutoff), lossy sparse error feedback, sharded-average
# bit-identity + 1/world reduce-state, and the mixed sparse/dense
# generation-tag regression (docs/ALLREDUCE.md sparse tier).
python -m pytest tests/test_allreduce.py -x -q \
    -k "Sparse or ChooseAlgo or Sharded"

echo "== allreduce engine (ring / rhalving / lossy EF / async writer) =="
python -m pytest tests/test_allreduce.py -x -q

echo "== sharding subset (routing equivalence / hot-shard replication) =="
# Multi-server invariants get their own named gate: 1-vs-N element-wise
# routing equivalence across all table types (boundary/off-by-one row
# splits included), the replica protocol's read-your-writes floor and
# version watermark, sticky promotion, and demotion pruning
# (tests/test_sharding.py; docs/SHARDING.md).
python -m pytest tests/test_sharding.py -x -q

echo "== resharding subset (elastic shard maps / live migration) =="
# Elastic-resharding invariants get their own named gate: shard-map
# algebra (epoch-0 equivalence to the frozen layout, move/coalesce,
# planning), the migration state machines (dirty re-streaming, seq-gap
# retransmits), mid-stream 1-vs-N equivalence across a live grow/
# shrink for matrix + KV with array/sparse siblings, the
# no-version-regression handoff property, the unsupported-table NACK
# rollback, and the in-process controller-partition chaos case
# (tests/test_resharding.py; docs/SHARDING.md "Elastic resharding").
# The SIGKILL chaos matrix (kill the migration source / destination
# mid-handoff) is subprocess-heavy and lives behind -m slow.
python -m pytest tests/test_resharding.py -x -q -m 'not slow'
if [ "${MV_CI_SLOW:-0}" = "1" ]; then
    echo "== slow chaos matrix (kill source / kill dest mid-handoff) =="
    python -m pytest tests/test_resharding.py -x -q -m slow
fi

echo "== autotune subset (dynamic flags / config broadcast / policies) =="
# The closed-loop self-tuning layer gets its own named gate: the
# TUNABLE_FLAGS dynamic-flag layer (apply hooks fire on broadcast,
# non-tunable flags rejected atomically, config-epoch regression
# ignored, weak hooks pruned), the Control_Config/Reply round trip,
# the rejoin config re-anchor, the AutotuneManager policies
# (SLO-gated widening/shrinking, hysteresis, cooldown, pinning,
# guardrails), live retunes of construction-time caches, and the
# ClusterMetrics ingest ordering guard (tests/test_autotune.py;
# docs/AUTOTUNE.md). The static half of the gate — tunable-lint —
# already ran in the mvlint block above.
python -m pytest tests/test_autotune.py -x -q -m 'not slow'

echo "== roles subset (thread-role registry / blocking watchdog / call graph) =="
# The thread-role layer gets its own named gate: the spawn contract
# (role registry, auto-start, live-registry drain), the -debug_locks
# blocking watchdog (fires on a deliberately-parked DISPATCH thread,
# silent on a clean 2-rank PS smoke), and the interprocedural call
# graph passes 9/10 stand on (method resolution under a subclass
# binding, Thread-target edges, functools.partial, recursion/depth
# bounds). The static half — thread-role + guarded-by — already ran
# in the mvlint block above. docs/THREADS.md.
python -m pytest tests/test_thread_roles.py tests/test_callgraph.py -x -q

echo "== event-loop transport subset (peer state machines / O(1) threads) =="
# The selector-loop transport core gets its own named gate: every peer
# state transition (CONNECTING -> HANDSHAKE -> READY -> DRAINING ->
# DEAD) driven over real loopback sockets, nonblocking connect backoff
# against a not-yet-bound listener, the connect-deadline typed failure,
# the idle-EOF quiet retire + same-endpoint rejoin, goodbye-draining
# finalize with a peer dying mid-drain, and the O(1)-threads-in-peers
# invariant. The conftest leak guard additionally asserts around EVERY
# test in the repo that role-thread and fd counts return to baseline
# (tests/test_event_loop.py; docs/THREADS.md).
python -m pytest tests/test_event_loop.py -x -q

echo "== server-fusion subset (mailbox drain / fused dispatch / fused == serial) =="
# The server execution engine's request fusion gets its own named
# gate: MtQueue.pop_batch drain semantics (high-watermark + push-side
# track_depth sampling preserved, byte cap bounds the tail, exit
# drains the remainder), the pure planner invariants (barrier
# classes, per-table op exclusivity, BatchAdd all-or-nothing), the
# dispatch protocol (arrival-order replies around barriers,
# post-batch version stamps, PartialFuseError prefix accounting,
# sync-mode force-disable), and the fused == serial equivalence
# integrations across all four table types + the read-your-writes
# floor + a -chaos_frames smoke (tests/test_server_fusion.py;
# docs/SERVER_ENGINE.md).
python -m pytest tests/test_server_fusion.py -x -q -m 'not slow'

echo "== obs subset (tracing / metrics export / scrape surface) =="
# Observability invariants get their own named gate: trace-id sampling
# and wire propagation (TRACE_SLOT, byte-identity when off), the span
# ring buffer + slow-request watchdog, snapshot/cluster aggregation +
# Prometheus text exposition validity, the /metrics//trace.json HTTP
# surface, and the 3-process TCP integration proof (cross-rank nested
# Get trace; cluster SERVER_PROCESS_GET == sum of per-rank dumps).
# docs/OBSERVABILITY.md.
python -m pytest tests/test_observability.py -x -q -m 'not slow'

echo "== serving subset (frontend / admission / staleness invariant) =="
# The online serving tier gets its own named gate: the shared HTTP
# base (route dispatch, typed errors), admission control (in-flight
# caps, depth shedding, 429 + Retry-After, graceful drain), mailbox
# depth observability, the versioned serving read's metadata, the
# /v1 endpoints, and the acceptance invariant — every served
# response's max_staleness respects the configured bound while a
# trainer pushes Adds concurrently (tests/test_serving.py;
# docs/SERVING.md).
python -m pytest tests/test_serving.py -x -q -m 'not slow'

echo "== serving-fleet subset (scatter-gather / batching / hot cache / ANN) =="
# The fleet read path gets its own named gate: scatter-gather reads
# with row-scoped partial-failure containment (dead shard owner ->
# retryable 503 on exactly the affected rows, never a wrong value),
# request-batching boundaries (window-deadline vs size-cap flush, the
# lone-request latency bound, batch error isolation), hot-response-
# cache freshness + the data-generation forced invalidation
# (reshard/rejoin), the IVF neighbors index (exactness at full probe,
# recall, the brute=1 escape), and the /v1/status fleet view
# (tests/test_serving_fleet.py; docs/SERVING.md fleet section).
python -m pytest tests/test_serving_fleet.py -x -q -m 'not slow'

echo "== fault-tolerance subset (snapshots / rejoin / backup workers) =="
# Crash-survival invariants get their own named gate: async snapshot
# consistency + restore, dead-peer containment and retry, the BSP
# backup-worker straggler cutoff, and the kill-a-server-mid-epoch
# integration proof (tests/test_fault_tolerance.py). The chaos smoke
# and the snapshot p99 bound are heavier and live behind -m slow — run
# `MV_CI_SLOW=1 ./ci.sh` (or pytest -m slow directly) to include them.
python -m pytest tests/test_fault_tolerance.py -x -q -m 'not slow'
if [ "${MV_CI_SLOW:-0}" = "1" ]; then
    echo "== slow chaos / latency-bound extras =="
    python -m pytest tests/test_fault_tolerance.py -x -q -m slow
fi

echo "== unit + in-process integration tests =="
# Virtual 8-device CPU mesh (tests/conftest.py forces the platform).
# Slow chaos/bench extras stay behind the -m slow gate above.
# test_fault_tolerance.py already ran in its named gate above — its
# kill-a-server integration proof spawns two full subprocess word2vec
# cluster runs, far too heavy to pay twice per CI pass.
python -m pytest tests/ -x -q -m 'not slow' \
    --ignore=tests/test_net_integration.py \
    --ignore=tests/test_fault_tolerance.py

echo "== multi-process TCP integration (the mpirun -np 4 equivalent) =="
python -m pytest tests/test_net_integration.py -x -q

echo "== c_api ABI through ctypes (+ Lua when a runtime exists) =="
python -m pytest tests/test_binding.py -x -q

echo "== runnable distributed example (2 OS processes, machine file) =="
python binding/python/examples/distributed_word2vec.py -n 2

echo "== CPU perf baseline builds and runs =="
g++ -O3 -fopenmp -o /tmp/w2v_baseline_ci native/baseline/word2vec_baseline.cpp
printf 'a b c d\nb a d c\n' > /tmp/w2v_ci_corpus.txt
/tmp/w2v_baseline_ci /tmp/w2v_ci_corpus.txt - 1 8 2 2 0 0.025 1

echo "== driver entry points =="
python -c "import __graft_entry__ as g; fn, a = g.entry(); fn(*a)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "CI OK"
